"""Pipeline split across two processes: a TPU-side server pipeline serves a
client pipeline over the native TCP transport (reference edge-ai offload).

Launch-string equivalents (pre-flight with ``nns-launch --check``):

    tensor_query_serversrc port=5001 !
        tensor_filter framework=jax model=zoo:add custom=dims:4,const:10 input=4 inputtype=float32 !
        tensor_query_serversink
    tensorsrc dimensions=4 num-frames=8 ! tensor_query_client dest-port=5001 ! tensor_sink
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import multiprocessing as mp
import threading


def server(port_q):
    from nnstreamer_tpu.edge.query import TensorQueryServerSrc, TensorQueryServerSink
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.pipeline.graph import Pipeline

    src = TensorQueryServerSrc(port=0)
    # serversrc emits format=flexible; declare the static input spec
    filt = TensorFilter(framework="jax", model="zoo:add", custom="dims:4,const:10",
                        input="4", inputtype="float32")
    sink = TensorQueryServerSink()
    p = Pipeline().chain(src, filt, sink)
    p.start()
    port_q.put(src.bound_port)
    threading.Event().wait()  # serve until the parent terminates us


if __name__ == "__main__":
    import numpy as np

    from nnstreamer_tpu.edge.query import TensorQueryClient
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import TensorSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    q = mp.Queue()
    proc = mp.Process(target=server, args=(q,), daemon=True)
    proc.start()
    port = q.get(timeout=30)

    src = TensorSrc(dimensions="4", types="float32", **{"num-frames": 3})
    client = TensorQueryClient(**{"dest-port": port})
    sink = TensorSink()
    Pipeline().chain(src, client, sink).run(timeout=60)
    for i, f in enumerate(sink.frames):
        print(f"reply {i}: {np.asarray(f.tensors[0])}")
    proc.terminate()
