"""Pipeline split across two processes: a TPU-side server pipeline serves a
client pipeline over the native TCP transport (reference edge-ai offload).

Launch-string equivalents (pre-flight with ``nns-launch --check``):

    tensor_query_serversrc port=5001 max-clients=4 max-inflight=16 !
        tensor_filter framework=jax model=zoo:add custom=dims:4,const:10 input=4 inputtype=float32 !
        tensor_query_serversink
    tensorsrc dimensions=4 num-frames=8 ! tensor_query_client dest-port=5001 ! tensor_sink

The server carries admission bounds (docs/edge-serving.md) — a query
server without any is the overload-collapse topology nns-lint flags as
NNS-W111.

Distributed tracing (docs/observability.md): run with NNS_TRACE_DIR=/tmp/t
and both processes record chrome traces — the client stamps each request
with a frame_id that rides the wire meta, so ``trace.merge()`` folds
client.json + server.json into ONE merged.json timeline where the client
span sits over the server-side work it caused (load it in Perfetto).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import multiprocessing as mp

TRACE_DIR = os.environ.get("NNS_TRACE_DIR")


def server(port_q, stop_q):
    from nnstreamer_tpu.edge.query import TensorQueryServerSrc, TensorQueryServerSink
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.pipeline.graph import Pipeline

    tracer = None
    if TRACE_DIR:
        from nnstreamer_tpu import trace as trace_mod

        tracer = trace_mod.enable()
        tracer.set_process("query-server")
    src = TensorQueryServerSrc(port=0, **{"max-clients": 4,
                                          "max-inflight": 16})
    # serversrc emits format=flexible; declare the static input spec
    filt = TensorFilter(framework="jax", model="zoo:add", custom="dims:4,const:10",
                        input="4", inputtype="float32")
    sink = TensorQueryServerSink()
    p = Pipeline().chain(src, filt, sink)
    ex = p.start()
    port_q.put(src.bound_port)
    stop_q.get()  # serve until the parent says stop
    ex.stop()
    if tracer is not None:
        tracer.save(os.path.join(TRACE_DIR, "server.json"))


if __name__ == "__main__":
    import numpy as np

    from nnstreamer_tpu.edge.query import TensorQueryClient
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import TensorSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    tracer = None
    if TRACE_DIR:
        from nnstreamer_tpu import trace as trace_mod

        os.makedirs(TRACE_DIR, exist_ok=True)
        tracer = trace_mod.enable()
        tracer.set_process("query-client")
    q = mp.Queue()
    stop_q = mp.Queue()
    proc = mp.Process(target=server, args=(q, stop_q), daemon=True)
    proc.start()
    port = q.get(timeout=30)

    src = TensorSrc(dimensions="4", types="float32", **{"num-frames": 3})
    client = TensorQueryClient(**{"dest-port": port})
    sink = TensorSink()
    Pipeline().chain(src, client, sink).run(timeout=60)
    for i, f in enumerate(sink.frames):
        print(f"reply {i}: {np.asarray(f.tensors[0])} "
              f"(frame_id={f.meta.get('frame_id')})")
    stop_q.put(None)  # let the server save its trace and exit cleanly
    proc.join(timeout=30)
    if tracer is not None:
        import json

        from nnstreamer_tpu import trace as trace_mod

        client_path = os.path.join(TRACE_DIR, "client.json")
        tracer.save(client_path)
        server_path = os.path.join(TRACE_DIR, "server.json")
        if os.path.exists(server_path):
            with open(client_path) as f1, open(server_path) as f2:
                merged = trace_mod.merge([json.load(f1), json.load(f2)])
            merged_path = os.path.join(TRACE_DIR, "merged.json")
            with open(merged_path, "w") as f:
                json.dump(merged, f)
            print(f"merged chrome trace: {merged_path} (open in Perfetto)")
        else:
            # server died or hung before saving: keep the client half
            print(f"server trace missing; client trace at {client_path}")
    if proc.is_alive():
        proc.terminate()
