"""One publisher, two subscribers via the vendored MQTT broker.

Launch-string equivalents (pre-flight with ``nns-launch --check``):

    videotestsrc num-frames=4 ! tensor_converter ! mqttsink pub-topic=demo/video
    mqttsrc sub-topic=demo/video ! tensor_sink
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()

import time

from nnstreamer_tpu.edge.mqtt import MqttBroker
from nnstreamer_tpu.edge.mqtt_elems import MqttSink, MqttSrc
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline

broker = MqttBroker()
print(f"broker on port {broker.port}")

subs = []
for i in range(2):
    sink = TensorSink()
    p = Pipeline().chain(
        MqttSrc(port=broker.port, **{"sub-topic": "demo/#"}), sink)
    subs.append((p, p.start(), sink))
time.sleep(0.3)

Pipeline().chain(
    VideoTestSrc(width=16, height=16, **{"num-frames": 5}),
    TensorConverter(),
    MqttSink(port=broker.port, **{"pub-topic": "demo/cam0"}),
).run(timeout=60)

for i, (p, ex, sink) in enumerate(subs):
    ex.wait(timeout=30)
    p.stop()
    print(f"subscriber {i}: received {sink.rendered} frames")
broker.close()
