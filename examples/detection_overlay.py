"""SSD detection with on-device NMS decoded to an RGBA overlay
(the reference's nnstreamer_decoder_boundingbox example pipeline).

Launch-string equivalent (pre-flight it with ``nns-launch --check``):

    videotestsrc width=300 height=300 num-frames=4 ! tensor_converter !
        tensor_filter framework=jax model=zoo:ssd_mobilenet_v2_pp custom=threshold:0.0001 !
        tensor_decoder mode=bounding_boxes option1=mobilenet-ssd-postprocess option4=300:300 !
        tensor_sink
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import numpy as np

from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.decoder import TensorDecoder
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline

src = VideoTestSrc(width=300, height=300, **{"num-frames": 4})
filt = TensorFilter(framework="jax", model="zoo:ssd_mobilenet_v2_pp",
                    custom="threshold:0.0001")
dec = TensorDecoder(mode="bounding_boxes",
                    option1="mobilenet-ssd-postprocess", option4="300:300")
sink = TensorSink()
Pipeline().chain(src, TensorConverter(), filt, dec, sink).run(timeout=300)
for i, f in enumerate(sink.frames):
    dets = f.meta["detections"]
    print(f"frame {i}: {dets.shape[0]} detections, overlay "
          f"{f.tensors[0].shape}")
