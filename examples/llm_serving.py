"""Continuous-batching LLM serving demo (models/serving.py).

Three requests of different lengths arrive at different times; the
batcher multiplexes them onto one fixed slot batch — two compiled XLA
programs total (prefill, batched step) for the server's whole life.
Greedy outputs are identical to serving each request alone.

Run: python examples/llm_serving.py    (CPU or TPU; small model)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import jax
import numpy as np

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

params = tfm.init_params(
    jax.random.PRNGKey(0), vocab=1024, d_model=128, n_heads=8, n_layers=2
)
cb = ContinuousBatcher(params, n_heads=8, n_slots=4, max_len=128,
                       prompt_len=32)
rng = np.random.default_rng(0)

print("submit A (prompt 20 tokens, want 12)")
ra = cb.submit(rng.integers(1, 1024, (20,)), 12)
steps = 0
for _ in range(4):
    cb.step()
    steps += 1
print("submit B mid-flight (prompt 7 tokens, want 8)")
rb = cb.submit(rng.integers(1, 1024, (7,)), 8)
print("submit C (prompt 30 tokens, want 5)")
rc = cb.submit(rng.integers(1, 1024, (30,)), 5)

while any(cb.result(r) is None for r in (ra, rb, rc)):
    emitted = cb.step()
    steps += 1
    print(f"  step {steps}: {len(emitted)} active slots emitted")

for name, rid in (("A", ra), ("B", rb), ("C", rc)):
    print(f"{name}: {cb.result(rid)}")
print(f"free slots at end: {cb.n_free}/4")

# ---- the pumped form: same streams, a fraction of the host traffic ----
# step() pays one dispatch + one [B] readback PER TOKEN; step_pump(n)
# scans n steps in one program with ONE [B, n] readback, and
# spec_pump(rounds, k) runs whole speculative rounds on device with
# proposals mined there (device_ngram_propose). On a remote-attached
# TPU each saved readback is a full round trip.
cb2 = ContinuousBatcher(params, n_heads=8, n_slots=4, max_len=128,
                        prompt_len=32)
rng = np.random.default_rng(0)
r2a = cb2.submit(rng.integers(1, 1024, (20,)), 12)
cb2.step_pump(4)
r2b = cb2.submit(rng.integers(1, 1024, (7,)), 8)
r2c = cb2.submit(rng.integers(1, 1024, (30,)), 5)
pumps = 0
while any(cb2.result(r) is None for r in (r2a, r2b, r2c)):
    out = cb2.step_pump(8)   # or cb2.spec_pump(rounds=2, k=4)
    pumps += 1
    total = sum(len(v) for v in out.values())
    print(f"  pump {pumps}: {total} tokens in one readback")
assert cb2.result(r2a) == cb.result(ra)  # pumped == per-token streams
assert cb2.result(r2b) == cb.result(rb)
assert cb2.result(r2c) == cb.result(rc)
print(f"pumped streams identical; host reads: {steps} per-token vs "
      f"{pumps + 1} pumped")

print("\n-- prefix caching: shared system prompt, prefilled once --")
system = rng.integers(1, 1024, (24,))
pid = cb.register_prefix(system)
rd = cb.submit(rng.integers(1, 1024, (6,)), 6, prefix=pid)
re_ = cb.submit(rng.integers(1, 1024, (9,)), 6, prefix=pid,
                temperature=0.8, seed=42)  # sampled, deterministic per seed
while cb.result(rd) is None or cb.result(re_) is None:
    cb.step()
print(f"D (greedy, shared prefix): {cb.result(rd)}")
print(f"E (sampled t=0.8, shared prefix): {cb.result(re_)}")
cb.unregister_prefix(pid)

print("\n-- sliding window: 200 tokens through a 64-slot ring --")
ring = ContinuousBatcher(params, n_heads=8, n_slots=1, max_len=64,
                         prompt_len=32, windowed=True)
rf = ring.submit(rng.integers(1, 1024, (20,)), 200)
while ring.result(rf) is None:
    ring.step()
print(f"F: {len(ring.result(rf))} tokens decoded in a fixed 64-token cache")

print("\n-- token streaming: partials() while slots decode --")
sb = ContinuousBatcher(params, n_heads=8, n_slots=2, max_len=96,
                       prompt_len=32)
rg = sb.submit(rng.integers(1, 1024, (12,)), 10)
seen = 0
while sb.result(rg) is None:
    sb.step()
    toks = sb.partials([rg]).get(rg, [])
    if len(toks) > seen:
        print(f"  streamed: +{toks[seen:]}")
        seen = len(toks)
print(f"G: {seen} tokens streamed as they decoded")

print("\n-- windowed long prompt: 150-token prompt into a 64 ring --")
wp = ContinuousBatcher(params, n_heads=8, n_slots=1, max_len=64,
                       prompt_len=32, windowed=True)
rh = wp.submit(rng.integers(1, 1024, (150,)), 8)
while wp.result(rh) is None:
    wp.step()
print(f"H: prompt 150 > ring 64 — exact sliding-window prefill, "
      f"{len(wp.result(rh))} tokens out")

print("\n-- speculative rounds: prompt-lookup, then a draft model --")
pattern = np.tile(np.asarray([5, 9, 13], np.int32), 6)
sp = ContinuousBatcher(params, n_heads=8, n_slots=2, max_len=128,
                       prompt_len=32)
ri = sp.submit(pattern, 16)
rj = sp.submit(rng.integers(1, 1024, (8,)), 8, temperature=0.7, seed=7)
while sp.result(ri) is None or sp.result(rj) is None:
    sp.spec_step(k=4, ngram=1)  # greedy exact; sampled distribution-exact
st = sp.stats()
print(f"I/J: {st['tokens_emitted']} tokens in {st['spec_rounds']} "
      f"verify rounds ({st['spec_accepted_tokens']} speculated tokens "
      "accepted)")

draft = tfm.init_params(
    jax.random.PRNGKey(9), vocab=1024, d_model=64, n_heads=4, n_layers=1
)
ds = ContinuousBatcher(params, n_heads=8, n_slots=2, max_len=128,
                       prompt_len=32, draft_params=draft, draft_n_heads=4)
rk = ds.submit(rng.integers(1, 1024, (10,)), 12)
while ds.result(rk) is None:
    ds.spec_step(k=4)
st = ds.stats()
print(f"K (draft model proposes): {st['tokens_emitted']} tokens, "
      f"{st['spec_accepted_tokens']} draft proposals accepted")

# ---- paged KV: block tables, prefix sharing, SLOs (nns-kv) ----
# kv_layout="paged" carves the cache into 16-token blocks behind
# per-request block tables (docs/llm-serving.md): requests hold only
# the blocks their tokens occupy, identical prompts share physical
# blocks through a rolling prefix hash, long prompts prefill in chunks
# interleaved with decode, and pool pressure preempts-and-re-prefills
# instead of OOMing. Decode is BLOCK-NATIVE by default (kv_attn="auto"
# → "block": attention reads ride the block tables straight off the
# arena, each token writes in place into its owning block — zero
# gather/scatter programs; kv_attn="gather" keeps the materialized-
# view oracle for parity debugging). Streams are bitwise the slot
# layout's either way.
print("\n-- paged KV cache: 12 requests in a 6-request HBM budget --")
pg = ContinuousBatcher(params, n_heads=8, n_slots=16, max_len=128,
                       prompt_len=32, kv_layout="paged", block_size=16,
                       kv_blocks=48)  # 48 blocks = 6 x max_len of HBM
system = rng.integers(1, 1024, (32,))  # shared system prompt: 2 blocks
rids = []
for i in range(12):
    user = rng.integers(1, 1024, (8,))
    rids.append(pg.submit(np.concatenate([system, user]), 10,
                          deadline_s=30.0))
while any(pg.result(r) is None for r in rids):
    pg.step_pump(8)
st = pg.stats()
print(f"L: {len(rids)} requests served in a {st['kv_blocks']}-block "
      f"arena; prefix hits {st['kv_prefix_hits']} "
      f"({st['kv_prefix_hit_tokens']} tokens never re-prefilled), "
      f"peak blocks in use ≤ {st['kv_blocks']}")
slo = pg.requests()
done = [v for v in slo.values() if v["state"] == "done"]
print(f"   SLO ledger: {len(done)} done, sample TTFT "
      f"{done[0]['ttft_ms']:.1f} ms, TPOT {done[0]['tpot_ms']:.2f} ms"
      if done else "")
