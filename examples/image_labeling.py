"""The v0 end-to-end slice (SURVEY.md §7 build order 2): deterministic
frames → fused normalize+MobileNet-v2 → argmax class indices.

Launch-string equivalent (pre-flight it with ``nns-launch --check``):

    videotestsrc width=224 height=224 num-frames=8 ! tensor_converter !
        tensor_filter framework=jax model=zoo:mobilenet_v2 !
        tensor_decoder mode=image_labeling ! tensor_sink
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import numpy as np

from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.decoder import TensorDecoder
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline

src = VideoTestSrc(width=224, height=224, **{"num-frames": 8})
filt = TensorFilter(framework="jax", model="zoo:mobilenet_v2")
dec = TensorDecoder(mode="image_labeling")
sink = TensorSink()
Pipeline().chain(src, TensorConverter(), filt, dec, sink).run(timeout=300)
for i, f in enumerate(sink.frames):
    print(f"frame {i}: class {int(np.asarray(f.tensors[0])[0])}")
