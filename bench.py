#!/usr/bin/env python
"""Benchmark: MobileNet-v2 224x224 single-chip streaming FPS.

The BASELINE.md north-star config: the reference's gst-launch MobileNet-v2
image-labeling pipeline, rebuilt TPU-native — uint8 frames in, logits out,
normalization fused into the jitted model, frames streamed with async
dispatch-ahead. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "fps", "vs_baseline": N, ...}
vs_baseline is against the 1000 FPS/chip target (BASELINE.json).

Robustness: the TPU backend attach over the tunnel is flaky (round-1 failure
mode: ``Unable to initialize backend 'axon': UNAVAILABLE`` at the first device
op, which jax then caches for the process lifetime). So this file is an
orchestrator: each attempt runs the measurement in a FRESH subprocess
(``bench.py --run``) with backoff between attempts; the final fallback attempt
pins the CPU platform so a diagnostic number always exists. On total failure
it still prints one parseable JSON line with the error tail instead of rc:1.

Measurement notes: jax dispatch is async; a streaming pipeline only
synchronizes when a sink consumes results on host. We sync on a bounded
in-flight window — the executor's sink path with ``sync-window=N``
(elements/base.py Sink, executor.py SinkNode) — which is the steady-state
pattern, not a per-frame round-trip (the tunnelled device adds ~70ms per
*sync*, not per dispatch, so per-frame blocking would measure the tunnel,
not the TPU). Stats hooks mirror the reference's measurement surface
(tensor_filter.c:334-433 latency/throughput properties).
"""

from __future__ import annotations

import json
import os
import platform as _platform
import statistics
import sys
import time

# bf16 peak TFLOP/s per chip by PJRT device_kind substring (public specs).
_PEAK_TFLOPS = {
    "v6e": 918.0,
    "v6": 918.0,
    "v5p": 459.0,
    "v5e": 197.0,
    "v5litepod": 197.0,
    "v5lite": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}


def _peak_tflops(device_kind: str) -> float | None:
    k = device_kind.lower().replace(" ", "")
    for key, val in _PEAK_TFLOPS.items():
        if key in k:
            return val
    return None


def _cost_analysis(fn, example) -> dict:
    """XLA's own cost analysis for one invoke, if available."""
    try:
        import jax

        cost = jax.jit(fn).lower(example).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost) if cost else {}
    except Exception:
        return {}


def _flops_per_frame(fn, example) -> float | None:
    f = float(_cost_analysis(fn, example).get("flops", 0.0))
    return f if f > 0 else None


def _mark(label: str, _t=[None]) -> None:
    """Section progress to stderr (the JSON protocol owns stdout)."""
    now = time.perf_counter()
    if _t[0] is not None:
        print(f"[bench] {label} (+{now - _t[0]:.1f}s)", file=sys.stderr)
    else:
        print(f"[bench] {label}", file=sys.stderr)
    _t[0] = now


def _round(v, nd=1):
    return round(v, nd) if v is not None else None


def _steady_fps(ex, scale: float = 1.0) -> float | None:
    """Steady-state sink FPS: frames after the first completed render
    burst / wall time (compile + warmup excluded). One definition for
    every pipeline cell — the steady window must not drift per cell."""
    from nnstreamer_tpu.pipeline.executor import SinkNode

    sink = next(n for n in ex.nodes if isinstance(n, SinkNode))
    steady = sink.frames_rendered - sink.first_burst_n
    if (
        sink.t_first_render is None
        or sink.t_last_render is None
        or steady < 1
        or sink.t_last_render <= sink.t_first_render
    ):
        return None
    return steady * scale / (sink.t_last_render - sink.t_first_render)


def _opt(label: str, fn):
    """Run one optional bench section; a failure nulls ITS cell only.
    A rare live relay window must record every other cell even when one
    section trips (the round-1 rc:1 lesson, applied uniformly)."""
    try:
        return fn()
    except Exception as exc:  # noqa: BLE001
        print(f"[bench] optional {label} failed: {exc!r}", file=sys.stderr)
        return None


def _run() -> None:
    """One measurement attempt (run in a fresh subprocess)."""
    run_start = time.perf_counter()
    plat = os.environ.get("BENCH_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    import jax
    import jax.numpy as jnp
    import numpy as np

    # attach probe with in-process retries (cheap transient errors)
    last = None
    for attempt in range(3):
        try:
            dev = jax.devices()[0]
            jax.block_until_ready(jnp.zeros((8,), jnp.float32) + 1.0)
            last = None
            break
        except Exception as exc:  # noqa: BLE001 — any attach error retries
            last = exc
            time.sleep(2.0 * (attempt + 1))
    if last is not None:
        raise last

    from nnstreamer_tpu.models import zoo

    _mark("attach ok")
    on_tpu = dev.platform == "tpu"
    batch = 1
    # CPU fallback exists to record a diagnostic number, not to spend 15
    # minutes interpreting convs — scale the loops down off-TPU
    iters = 1024 if on_tpu else 48
    warmup = 20 if on_tpu else 3
    sync_every = 256 if on_tpu else 16

    m = zoo.get("mobilenet_v2", batch=str(batch), compute_dtype="bfloat16")
    fn = jax.jit(m.fn)
    rng = np.random.default_rng(0)
    frames = [
        jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3), np.uint8))
        for _ in range(8)
    ]

    # warmup / compile
    out = None
    for i in range(warmup):
        out = fn(frames[i % len(frames)])
    jax.block_until_ready(out)

    _mark("bs1 compiled+warm")
    # throughput: stream with bounded dispatch-ahead window. The device
    # runs dispatches in order, so syncing the window's LAST result fences
    # the whole window without touching every handle.
    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        out = fn(frames[i % len(frames)])
        if (i + 1) % sync_every == 0:
            out.block_until_ready()
    out.block_until_ready()
    dt = time.perf_counter() - t0
    fps = iters * batch / dt

    _mark("bs1 measured")
    # p50 sync round-trip latency (includes device-tunnel RTT when remote)
    lat = []
    for i in range(50 if on_tpu else 8):
        t = time.perf_counter()
        fn(frames[i % len(frames)]).block_until_ready()
        lat.append((time.perf_counter() - t) * 1000)
    p50 = statistics.median(lat)

    _mark("p50 measured")
    # streaming-ingest variant: fresh host frame every iteration, staged
    # through the transfer engine (pipeline/transfer.py stage_iter): a
    # feeder thread keeps up to 3 async device_put uploads in flight, so
    # frame N+1's wire time overlaps frame N's compute — the executor's
    # resident-streaming H2D discipline, vs the on-device-resident loop
    # above. On CPU the stager passes host frames through (the jitted
    # ingest IS the cheaper copy), so the number converges on raw invoke.
    from nnstreamer_tpu.pipeline import transfer as _transfer

    host_frames = [
        np.ascontiguousarray(rng.integers(0, 255, (batch, 224, 224, 3), np.uint8))
        for _ in range(8)
    ]
    iters_h = 512 if on_tpu else 24
    out = None
    t0 = time.perf_counter()
    staged = _transfer.stage_iter(
        (host_frames[i % 8] for i in range(iters_h)),
        device=dev if on_tpu else None,
    )
    for i, x in enumerate(staged):
        out = fn(x)
        if (i + 1) % 128 == 0:
            out.block_until_ready()
    out.block_until_ready()
    h2d_fps = iters_h * batch / (time.perf_counter() - t0)

    _mark("h2d measured")
    # micro-batched variant: the reference's converter frames-per-tensor
    # batching (gsttensor_converter.c frames_per_tensor) maps to the
    # aggregator batching 8 frames per invoke — same pipeline semantics,
    # amortizing the per-dispatch cost the bs1 number is bound by.
    mb = 8
    m8 = zoo.get("mobilenet_v2", batch=str(mb), compute_dtype="bfloat16")
    fn8 = jax.jit(m8.fn)
    frames8 = [
        jnp.asarray(rng.integers(0, 255, (mb, 224, 224, 3), np.uint8))
        for _ in range(4)
    ]
    out = fn8(frames8[0])
    jax.block_until_ready(out)
    iters8 = 256 if on_tpu else 8
    t0 = time.perf_counter()
    for i in range(iters8):
        out = fn8(frames8[i % 4])
        if (i + 1) % 64 == 0:
            out.block_until_ready()
    out.block_until_ready()
    mb_fps = iters8 * mb / (time.perf_counter() - t0)

    _mark("mb8 measured")

    # ---- THE PIPELINE METRIC (BASELINE.md's actual target) ----
    # Everything above measures raw jitted invokes; BASELINE.md's bar is
    # the gst-launch-equivalent *pipeline*: videotestsrc !
    # tensor_converter ! tensor_filter ! tensor_decoder ! tensor_sink
    # through the streaming executor (threads, queues, Frame wrapping,
    # sink fencing — every cost the framework itself adds). The
    # converter/filter/decoder chain FUSES into one XLA program
    # (pipeline/graph.py), the decoder's argmax runs on device, and the
    # sink fences a sync-window — so the steady state is one async
    # dispatch per frame with no per-frame host round-trip.
    def _pipeline_fps(device_src, fpt, n_frames, window, timeout=900.0):
        """Steady-state pipeline FPS: frames after the first completed
        render burst / wall time (excludes compile+warmup)."""
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        # queue-size on the converter sizes the fused node's input queue
        # (the source→segment edge): deep dispatch-ahead lets the source
        # run ahead of the device stream instead of stalling at 4 frames
        conv = "tensor_converter queue-size=128" + (
            f" frames-per-tensor={fpt}" if fpt > 1 else ""
        )
        # per-frame host ingress stages uploads in a dedicated node: the
        # stage thread device_puts frame N+1 while the filter node
        # dispatches compute on frame N (elements/stage.py; the r2
        # 89.7-fps cliff was upload serialized with dispatch). NOT for
        # frames-per-tensor batching: the converter batches on HOST, so
        # a pre-staged frame would be read straight back (D2H per frame
        # — worse than the unstaged path it replaces)
        # the sink must flush SEVERAL windows or the steady-state
        # definition has no steady region (first burst excluded): with
        # fpt-batching the sink renders n_frames/fpt times, so clamp
        # the window to a quarter of that (the CPU-scale mb cells were
        # structurally null — one flush at EOS, zero steady frames)
        window = max(1, min(window, n_frames // fpt // 4))
        stage = (
            "" if device_src
            else "tensor_stage queue-size=128 ! "
        )
        # per-frame ingest stages BEFORE the converter (upload raw
        # frames); frames-per-tensor ingest batches on HOST first, so
        # the staged upload goes AFTER the converter — one device_put
        # per [fpt, ...] batch, overlapping the previous batch's compute
        pre = stage if fpt == 1 else ""
        post = stage if fpt > 1 else ""
        desc = (
            f"videotestsrc pattern=gradient device="
            f"{'true' if device_src else 'false'} "
            f"num-frames={n_frames} width=224 height=224 ! {pre}{conv} ! "
            f"{post}"
            f"tensor_filter framework=jax model=zoo:mobilenet_v2 "
            f'custom="batch:{fpt},compute_dtype:bfloat16" ! '
            "tensor_decoder mode=image_labeling ! "
            f"tensor_sink sync-window={window} queue-size=128"
        )
        p = parse_pipeline(desc)
        return _steady_fps(p.run(timeout=timeout), scale=fpt)

    # device-resident source: the framework + compute ceiling (frames
    # born on device, as in a chained-filter pipeline — BASELINE.md's
    # "device-resident tensors across chained filters, no host readback").
    # Guarded: a stalled executor or node error must degrade to a null
    # cell, never discard the raw metrics already measured above (the
    # round-1 rc:1 failure mode).
    def _pipeline_fps_safe(*args, **kw):
        try:
            return _pipeline_fps(*args, **kw)
        except Exception as exc:  # noqa: BLE001 — any pipeline failure
            print(f"[bench] pipeline variant failed: {exc!r}", file=sys.stderr)
            return None

    n_pipe = 4096 if on_tpu else 40
    pipe_window = 512 if on_tpu else 8
    pipeline_fps = _pipeline_fps_safe(True, 1, n_pipe, pipe_window)
    _mark("pipeline measured")

    # p50 END-TO-END frame latency through the pipeline (BASELINE's
    # tracked-latency config): wall-stamped frames from a PACED source
    # (is-live, below the sustainable rate — a free-running source
    # floods the queues and a wall-stamped p50 then measures BACKLOG,
    # not service time), per-frame sink sync (sync-window=1 — the
    # latency-honest configuration; on a tunneled device this includes
    # the RTT every frame, by design)
    def _paced_p50_ms(extra: str, n: int, fps: int):
        from nnstreamer_tpu.pipeline.executor import SinkNode
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        desc = (
            f"videotestsrc pattern=gradient device=true stamp-wall=true "
            f"is-live=true framerate={fps}/1 "
            f"num-frames={n} width=224 height=224 ! tensor_converter ! "
            f"{extra}"
            "tensor_filter framework=jax model=zoo:mobilenet_v2 "
            'custom="batch:1,compute_dtype:bfloat16" ! '
            "tensor_decoder mode=image_labeling ! tensor_sink sync-window=1"
        )
        p = parse_pipeline(desc)
        ex = p.run(timeout=600)
        sink = next(nd for nd in ex.nodes if isinstance(nd, SinkNode))
        # drop the first renders (compile/warmup rides on them), then
        # take the median of the steady tail — the TAIL quantiles ride
        # along (nns-obs discipline: means hide the p99 story)
        all_lats = list(sink.latencies)
        lats = all_lats[max(2, len(all_lats) // 8):]
        if not lats:
            return None, ex
        lats.sort()

        def _q(q: float) -> float:
            return 1000.0 * lats[min(len(lats) - 1, int(q * len(lats)))]

        return {"p50": _q(0.50), "p95": _q(0.95), "p99": _q(0.99)}, ex

    def _pipeline_lat_ms():
        return _paced_p50_ms(
            "", 48 if on_tpu else 8, 8 if on_tpu else 2
        )[0]

    pipeline_p95_ms = pipeline_p99_ms = None
    try:
        _lat = _pipeline_lat_ms()
        pipeline_p50_ms = _lat["p50"] if _lat else None
        pipeline_p95_ms = _lat["p95"] if _lat else None
        pipeline_p99_ms = _lat["p99"] if _lat else None
    except Exception as exc:  # noqa: BLE001
        print(f"[bench] pipeline p50 failed: {exc!r}", file=sys.stderr)
        pipeline_p50_ms = None
    _mark("pipeline p50 measured")

    # drop-to-deadline: a paced source ABOVE the sustainable rate with
    # tensor_rate holding a stated budget — the held p50 of SURVIVING
    # frames plus the drop rate is the latency-budget story
    # (gsttensor_rate.c:27-36 dup/drop discipline; BASELINE.md "p50 e2e
    # frame latency tracked"). The rate floor keeps offered load at 4×
    # the rate element's ceiling, so ~75% must drop while survivors
    # stay under budget.
    def _pipeline_rate_budget():
        hold = 4 if on_tpu else 1
        offered = hold * 4
        n = (48 if on_tpu else 12) * 4
        lat, ex = _paced_p50_ms(
            f"tensor_rate framerate={hold}/1 throttle=false ! ",
            n, offered,
        )
        p50 = lat["p50"] if lat else None
        from nnstreamer_tpu.elements.windowing import TensorRate
        from nnstreamer_tpu.pipeline.executor import SinkNode

        dropped = sum(
            nd.elem.drop + nd.elem.qos.skipped_upstream
            for nd in ex.nodes
            if isinstance(getattr(nd, "elem", None), TensorRate)
        )
        survived = sum(
            nd.frames_rendered for nd in ex.nodes
            if isinstance(nd, SinkNode)
        )
        total = dropped + survived
        drop_pct = round(100.0 * dropped / total, 1) if total else None
        return p50, drop_pct

    pipeline_rate_p50_ms = rate_drop_pct = None
    try:
        pipeline_rate_p50_ms, rate_drop_pct = _pipeline_rate_budget()
    except Exception as exc:  # noqa: BLE001
        print(f"[bench] rate budget failed: {exc!r}", file=sys.stderr)
    _mark("pipeline rate budget measured")

    # EARLY partial capture: the headline + primary cells land ~10 min
    # into a TPU window while the full optional ladder needs ~30+; a
    # window (or the round) ending mid-run must not lose the headline.
    # The end-of-run record replaces this (partial records never win
    # best-by-value against a full one, and a full one always replaces
    # a partial).
    if on_tpu:
        try:
            headline = pipeline_fps if pipeline_fps is not None else fps
            _record_measured(json.dumps({
                "metric": (
                    "mobilenet_v2_224_pipeline_fps_per_chip"
                    if pipeline_fps is not None
                    else "mobilenet_v2_224_bs1_fps_per_chip"
                ),
                "value": _round(headline),
                "unit": "fps",
                "vs_baseline": _round(headline / 1000.0, 3),
                "partial": True,
                "pipeline_fps": _round(pipeline_fps),
                "pipeline_p50_e2e_ms": _round(pipeline_p50_ms, 3),
                "pipeline_p95_e2e_ms": _round(pipeline_p95_ms, 3),
                "pipeline_p99_e2e_ms": _round(pipeline_p99_ms, 3),
                "pipeline_rate_p50_ms": _round(pipeline_rate_p50_ms, 3),
                "rate_drop_pct": rate_drop_pct,
                "raw_invoke_bs1_fps": _round(fps),
                "p50_sync_latency_ms": round(p50, 3),
                "h2d_streaming_fps": round(h2d_fps, 1),
                "microbatch8_fps": round(mb_fps, 1),
                "platform": dev.platform,
                "device": str(dev.device_kind),
            }))
        except Exception as exc:  # noqa: BLE001 — strictly additive
            print(f"[bench] partial capture failed: {exc!r}",
                  file=sys.stderr)

    # Optional sections below run inside a soft budget: the primary
    # metrics are already measured, and a slow tunnel day must not turn a
    # recorded number into an rc:1 (the round-1 failure mode).
    soft_budget = float(os.environ.get("BENCH_SOFT_BUDGET_S", "700"))

    def _over_budget() -> bool:
        # optional sections are TPU evidence; the CPU fallback records the
        # primary diagnostics only. BENCH_FORCE_OPTIONAL=1 runs them on
        # CPU anyway (scaled down) — the validation mode that proves the
        # capture-day code paths execute before a rare relay window
        # spends itself discovering a crash.
        if os.environ.get("BENCH_FORCE_OPTIONAL"):
            return time.perf_counter() - run_start > soft_budget
        return (not on_tpu) or time.perf_counter() - run_start > soft_budget

    # host-ingest pipeline variants: per-frame upload (honest camera-path
    # number — tunnel-RTT-bound when remote-attached) and frames-per-
    # tensor batched ingest (the converter batches 8/32 frames per
    # tensor, amortizing the per-transfer cost; reference
    # gsttensor_converter.c frames_per_tensor)
    # ALWAYS recorded, both platforms (VERDICT r4 #3): 89.7 fps on the
    # only TPU capture is the scariest number on record, so this cell
    # needs a round-over-round trend line even relay-dead. The pipeline
    # stages uploads in a dedicated node (tensor_stage: device_put of
    # frame N+1 overlaps compute of N — elements/stage.py).
    pipeline_h2d_fps = _pipeline_fps_safe(False, 1, 256 if on_tpu else 24, 16)
    _mark("pipeline-h2d measured")
    pipeline_mb8_fps = (
        None if _over_budget()
        else _pipeline_fps_safe(False, 8, 1024 if on_tpu else 64, 16)
    )
    _mark("pipeline-mb8 measured")
    pipeline_mb32_fps = (
        None if _over_budget()
        else _pipeline_fps_safe(False, 32, 2048 if on_tpu else 128, 8)
    )
    _mark("pipeline-mb32 measured")
    # device-source microbatch: frames born on device, batched on device
    # (converter jnp.stack — no host hop anywhere), 32/invoke. The
    # chained-filter configuration at the MXU's preferred batch: this is
    # the pipeline number that should approach raw microbatch32_fps,
    # separating framework overhead from link bandwidth (which bounds
    # the host-ingest mb cells above).
    pipeline_mb32_dev_fps = (
        None if _over_budget()
        else _pipeline_fps_safe(True, 32, 4096 if on_tpu else 128, 8)
    )
    _mark("pipeline-mb32-dev measured")

    # BRANCHED pipeline (reference parallelism construct #2, SURVEY
    # §2.6): tee → two model branches → mux(slowest) → sink. Unlike the
    # linear chain, nothing fuses across the tee/mux, so every frame
    # pays real multi-node executor traffic (2 extra nodes + 3 extra
    # queue hops + sync-policy grouping) on top of two model dispatches
    # — the host-path pressure case the linear pipeline_fps hides.
    def _pipeline_branched_fps(n_frames: int) -> float | None:
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        desc = (
            f"videotestsrc pattern=gradient device=true "
            f"num-frames={n_frames} width=224 height=224 ! "
            "tensor_converter queue-size=128 ! tee name=t "
            "t. ! queue ! tensor_filter framework=jax "
            'model=zoo:mobilenet_v2 custom="compute_dtype:bfloat16" ! '
            "m.sink_0 "
            "t. ! queue ! tensor_filter framework=jax "
            'model=zoo:mobilenet_v2 custom="compute_dtype:bfloat16" ! '
            "m.sink_1 "
            "tensor_mux name=m sync-mode=slowest ! "
            "tensor_demux tensorpick=0 ! tensor_decoder "
            "mode=image_labeling ! tensor_sink sync-window=16 "
            "queue-size=128"
        )
        p = parse_pipeline(desc)
        return _steady_fps(p.run(timeout=900))

    pipeline_branched_fps = None
    if not _over_budget():
        try:
            pipeline_branched_fps = _pipeline_branched_fps(
                512 if on_tpu else 48  # >1 sync burst or no steady window
            )
        except Exception as exc:  # noqa: BLE001
            print(f"[bench] branched pipeline failed: {exc!r}",
                  file=sys.stderr)
    _mark("pipeline-branched measured")

    # REAL-MEDIA pipeline: encoded clip → videofilesrc (decode-ahead
    # thread) → converter → mobilenet → decoder → sink. The honest
    # camera-path number including actual ffmpeg decode, with decode
    # overlapped against upload/inference (elements/media.py r4).
    def _pipeline_media_fps(n_frames: int) -> float | None:
        import tempfile

        try:
            import cv2
        except ImportError:
            return None
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        tmp = tempfile.TemporaryDirectory()
        path = os.path.join(tmp.name, "bench_clip.mp4")
        wr = cv2.VideoWriter(
            path, cv2.VideoWriter_fourcc(*"mp4v"), 30.0, (224, 224)
        )
        if not wr.isOpened():
            return None
        clip_len = 120
        for i in range(clip_len):
            wr.write(
                rng.integers(0, 255, (224, 224, 3), np.uint8)
                if i % 30 == 0 else np.full((224, 224, 3), i, np.uint8)
            )
        wr.release()
        desc = (
            f"videofilesrc location={path} loop=true "
            f"num-frames={n_frames} queue-size=128 ! "
            "tensor_converter queue-size=128 ! "
            "tensor_filter framework=jax model=zoo:mobilenet_v2 "
            'custom="compute_dtype:bfloat16" ! '
            "tensor_decoder mode=image_labeling ! "
            "tensor_sink sync-window=16 queue-size=128"
        )
        p = parse_pipeline(desc)
        try:
            return _steady_fps(p.run(timeout=900))
        finally:
            tmp.cleanup()

    pipeline_media_fps = None
    if not _over_budget():
        try:
            pipeline_media_fps = _pipeline_media_fps(
                512 if on_tpu else 48  # >1 sync burst or no steady window
            )
        except Exception as exc:  # noqa: BLE001
            print(f"[bench] media pipeline failed: {exc!r}", file=sys.stderr)
    _mark("pipeline-media measured")

    # batched-ingest variant: fresh host frames, but 8 per transfer (the
    # converter's frames-per-tensor batching) — one device_put per invoke
    # amortizes the per-transfer cost that bounds the per-frame H2D number
    # above (dominant when the device is tunnel-attached).
    def _h2d_b8():
        host8 = [
            np.ascontiguousarray(
                rng.integers(0, 255, (mb, 224, 224, 3), np.uint8)
            )
            for _ in range(4)
        ]
        iters_b = 128 if on_tpu else 8
        out = None
        t0 = time.perf_counter()
        for i in range(iters_b):
            x = jax.device_put(host8[i % 4], dev)
            out = fn8(x)
            if (i + 1) % 32 == 0:
                out.block_until_ready()
        out.block_until_ready()
        return iters_b * mb / (time.perf_counter() - t0)

    # always recorded (VERDICT r4 #3): the amortized-transfer companion
    # to pipeline_h2d_fps needs the same CPU trend line
    h2d_b8_fps = _opt("h2d_b8", _h2d_b8)

    _mark("h2d-batched8 measured")

    # composite face→crop→landmark pipeline (BASELINE config #5) through
    # the real pipeline executor, with the DEVICE-RESIDENT crop
    # (tensor_crop out-size=: fixed-size crop+resample in HBM, static
    # downstream spec — elements/control.py). No host hop at the crop:
    # regions stay device arrays, the landmark net compiles once and
    # serves all 16 crop slots as one MXU batch. This is the element
    # cascade measured against the fused single-program form below —
    # r2's 860x cliff (1.8 vs 1547 fps) came from host readbacks +
    # per-shape recompiles; the device crop removes both. The cell
    # itself is module-level (_composite_face_cell) and shared with
    # --gate, so the recorded and the gate-fresh numbers can never
    # drift methodologically.
    composite_fps = (
        None if _over_budget() else _opt("composite", _composite_face_cell)
    )

    _mark("composite measured")
    # fused form of the same cascade: detect→crop+resize→landmark as ONE
    # XLA program (zoo:face_composite), no host hop at the crop — the
    # TPU-first redesign the element composite above is measured against
    def _fused():
        mfc = zoo.get("face_composite", compute_dtype="bfloat16")
        fnc = jax.jit(mfc.fn)
        fframes = [
            jnp.asarray(rng.integers(0, 255, (1, 128, 128, 3), np.uint8))
            for _ in range(4)
        ]
        jax.block_until_ready(fnc(fframes[0]))
        iters_f = 512 if on_tpu else 16
        t0 = time.perf_counter()
        out = None
        for i in range(iters_f):
            out = fnc(fframes[i % 4])
            if (i + 1) % 128 == 0:
                jax.block_until_ready(out)
        jax.block_until_ready(out)
        return iters_f / (time.perf_counter() - t0)

    fused_fps = None if _over_budget() else _opt("fused", _fused)

    _mark("fused measured")
    # long-context serving: KV-cache greedy decode throughput (the
    # transformer_lm zoo model in generate mode — models/decode.py, one
    # prefill program + one scanned decode program)
    lm_kw = dict(
        vocab="32000", d_model="512", n_heads="8", n_layers="4",
        seqlen="128", compute_dtype="bfloat16",
    )
    toks = jnp.asarray(rng.integers(0, 32000, (1, 128), np.int64), jnp.int32)

    def _lm_tok_s(tokens=None, **extra):
        inp = toks if tokens is None else tokens
        mlm = zoo.get("transformer_lm", generate="64", **lm_kw, **extra)
        lm_fn = jax.jit(mlm.fn)
        jax.block_until_ready(lm_fn(inp))  # compile prefill + decode scan
        iters_lm = 8 if on_tpu else 1
        t0 = time.perf_counter()
        out = None
        for _ in range(iters_lm):
            out = lm_fn(inp)
        jax.block_until_ready(out)
        return iters_lm * 64 / (time.perf_counter() - t0)

    lm_tok_s = None if _over_budget() else _opt("lm", _lm_tok_s)
    _mark("lm measured")
    # weight-only int8 decode (models/quantize.py quantize_lm_weights):
    # decode reads every weight per token, so bytes/weight sets tok/s
    lm_int8w_tok_s = (
        None if _over_budget()
        else _opt("lm-int8w", lambda: _lm_tok_s(quantize="int8w"))
    )
    _mark("lm-int8w measured")
    # scanned n-gram speculation (decode:ngram): the WHOLE speculative
    # generation as one compiled program (device while_loop, on-device
    # mining — speculative.ngram_generate_scanned). A repetitive prompt
    # is the miner's best case, so this cell bounds the machinery's
    # speedup over the greedy scan above.
    rep_toks = jnp.asarray(
        np.tile(rng.integers(1, 32000, (8,)), 16)[None, :], jnp.int32
    )

    lm_ngram_tok_s = (
        None if _over_budget()
        else _opt(
            "lm-ngram",
            lambda: _lm_tok_s(
                tokens=rep_toks, decode="ngram", spec_ngram="1"
            ),
        )
    )
    _mark("lm-ngram measured")
    # continuous batching (models/serving.py): 4 slots decoding together —
    # one batched step program amortizes the per-token dispatch + weight
    # reads over every active stream
    lm_cb_tok_s = lm_cb_spec_ngram_tok_s = lm_cb_spec_draft_tok_s = None
    if not _over_budget():
        from nnstreamer_tpu.models import serving as srv

        mlm = zoo.get("transformer_lm", **lm_kw)
        # repetitive prompts so prompt-lookup proposals can land (the
        # spec cells measure the MACHINERY's throughput; acceptance on
        # a random-weight model is the worst case for ngram)
        base = rng.integers(1, 32000, (12,)).astype(np.int32)
        prompts = [np.tile(base, 4) for _ in range(4)]

        def _cb_tok_s(pump, **cb_kw):
            cb = srv.ContinuousBatcher(
                mlm.params, 8, n_slots=4, max_len=448, prompt_len=64,
                compute_dtype=jnp.bfloat16, **cb_kw,
            )

            def _drain(budget):
                rids = [cb.submit(p, budget) for p in prompts]
                while any(cb.result(r) is None for r in rids):
                    pump(cb)
                return 4 * budget

            _drain(4)  # compile prefill + step/verify programs
            t0 = time.perf_counter()
            n = _drain(64 if on_tpu else 8)
            return n / (time.perf_counter() - t0)

        # pump APIs (serving.py step_pump/spec_pump): N tokens or R
        # whole speculative rounds per program launch, ONE device→host
        # read per pump — the framework's serving hot path. Per-token
        # step() pays a full sync per token (ruinous through the
        # device tunnel: ~RTT/token).
        lm_cb_tok_s = _opt(
            "lm-cb4", lambda: _cb_tok_s(lambda cb: cb.step_pump(16))
        )
        _mark("lm-cb4 measured")
        # speculative pumps: prompt-lookup (free proposals) vs a draft
        # model (d128/L2 proposing for the d512/L4 target) — the tok/s
        # comparison VERDICT r3 #5 asks for
        if not _over_budget():
            lm_cb_spec_ngram_tok_s = _opt(
                "lm-cb4-spec-ngram",
                lambda: _cb_tok_s(
                    lambda cb: cb.spec_pump(rounds=4, k=4, ngram=1)
                ),
            )
            _mark("lm-cb4-spec-ngram measured")
        if not _over_budget():

            def _draft_cell():
                mdraft = zoo.get(
                    "transformer_lm", vocab="32000", d_model="128",
                    n_heads="8", n_layers="2", seqlen="128",
                    compute_dtype="bfloat16",
                )
                return _cb_tok_s(
                    lambda cb: cb.spec_pump(rounds=4, k=4),
                    draft_params=mdraft.params, draft_n_heads=8,
                )

            lm_cb_spec_draft_tok_s = _opt(
                "lm-cb4-spec-draft", _draft_cell
            )
            _mark("lm-cb4-spec-draft measured")
    # deep microbatch: 32 frames/invoke — past the dispatch-bound knee,
    # so this is the number that reflects device compute, not per-call
    # overhead (and the MFU that is fair to judge the chip against)
    mb32 = 32
    m32 = frames32 = None

    def _mb32():
        nonlocal m32, frames32
        m32 = zoo.get(
            "mobilenet_v2", batch=str(mb32), compute_dtype="bfloat16"
        )
        fn32 = jax.jit(m32.fn)
        frames32 = [
            jnp.asarray(rng.integers(0, 255, (mb32, 224, 224, 3), np.uint8))
            for _ in range(2)
        ]
        jax.block_until_ready(fn32(frames32[0]))
        iters32 = 64 if on_tpu else 2
        t0 = time.perf_counter()
        out = None
        for i in range(iters32):
            out = fn32(frames32[i % 2])
            if (i + 1) % 16 == 0:
                out.block_until_ready()
        out.block_until_ready()
        return iters32 * mb32 / (time.perf_counter() - t0)

    mb32_fps = None if _over_budget() else _opt("mb32", _mb32)

    _mark("mb32 measured")
    # compute-dense config: ViT-S/16. MobileNet-v2's depthwise convs
    # are MXU-hostile (9 MACs/output on a 128×128 systolic array) and
    # its 1×1 convs are bandwidth-bound at small batch — its MFU
    # ceiling is architectural, not a framework defect (roofline in
    # docs/BENCH_NOTES.md). A ViT is wall-to-wall dense matmuls, so
    # THIS cell is the one that can show the MXU actually fed.
    mv = vframes = None
    vit_flops = None
    vit_bytes = [None]  # filled by _vit32's single cost-analysis pass

    def _vit32():
        nonlocal mv, vframes, vit_flops
        mv = zoo.get("vit", batch=str(mb32), compute_dtype="bfloat16")
        fnv = jax.jit(mv.fn)
        vframes = [
            jnp.asarray(rng.integers(0, 255, (mb32, 224, 224, 3), np.uint8))
            for _ in range(2)
        ]
        jax.block_until_ready(fnv(vframes[0]))
        iters_v = 64 if on_tpu else 2
        t0 = time.perf_counter()
        out = None
        for i in range(iters_v):
            out = fnv(vframes[i % 2])
            if (i + 1) % 16 == 0:
                out.block_until_ready()
        out.block_until_ready()
        cost = _cost_analysis(mv.fn, vframes[0])
        vit_flops = float(cost.get("flops", 0.0)) or None
        vit_bytes[0] = float(cost.get("bytes accessed", 0.0)) or None
        return iters_v * mb32 / (time.perf_counter() - t0)

    vit32_fps = None if _over_budget() else _opt("vit-mb32", _vit32)

    _mark("vit-mb32 measured")
    # int8 serving path (models/quantize.py): the reference's
    # *_quant.tflite slot — same microbatch as mb8 so the two numbers
    # isolate the dtype effect. Measures the END-TO-END quantized path
    # (quantize=int8w, docs/on-device-ops.md): int8 weights resident
    # with the dequant epilogue fused into the segment, no
    # per-activation quant math — the configuration that beats fp
    # instead of trailing it (the old activation-quant path stays
    # available as quantize=int8 and is parity-pinned in
    # tests/test_quantize.py). Module-level cell shared with --gate;
    # the record stamps int8_impl so the gate never compares the new
    # configuration against an old activation-quant capture.
    int8_fps = None if _over_budget() else _opt("int8", _int8_mb8_cell)

    _mark("int8 measured")

    # host-path executor ceilings (see _executor_ceilings):
    # median-of-3 short runs, spread recorded beside the value
    executor_chain_fps = executor_branched_fps = None
    chain_program_fps = chain_program_pernode_fps = None
    ceiling_spreads = {}
    try:
        (executor_chain_fps, executor_branched_fps, chain_program_fps,
         chain_program_pernode_fps, ceiling_spreads) = _executor_ceilings()
    except Exception as exc:  # noqa: BLE001
        print(f"[bench] executor ceilings failed: {exc!r}", file=sys.stderr)
    overlap_efficiency = None
    try:
        overlap_efficiency = _overlap_efficiency()
    except Exception as exc:  # noqa: BLE001
        print(f"[bench] overlap efficiency failed: {exc!r}", file=sys.stderr)
    _mark("executor ceilings measured")

    # achieved MFU from XLA cost analysis + public per-chip peak
    flops = _flops_per_frame(m.fn, frames[0])
    peak = _peak_tflops(str(dev.device_kind))
    mfu = mfu8 = mfu32 = mfu_vit32 = None
    mbv2_bytes32 = None
    if flops and peak:
        mfu = fps * flops / (peak * 1e12)
        flops8 = _flops_per_frame(m8.fn, frames8[0])
        if flops8:
            mfu8 = mb_fps * (flops8 / mb) / (peak * 1e12)
    if mb32_fps:
        # ONE lowering serves both the MFU numerator and the roofline
        # bytes (a second .compile() of the batch-32 program would cost
        # multi-second XLA time in-budget). Outside the peak gate: the
        # roofline bytes must record even on a chip generation missing
        # from _PEAK_TFLOPS.
        cost32 = _cost_analysis(m32.fn, frames32[0])
        flops32 = float(cost32.get("flops", 0.0)) or None
        mbv2_bytes32 = float(cost32.get("bytes accessed", 0.0)) or None
        if flops32 and peak:
            mfu32 = mb32_fps * (flops32 / mb32) / (peak * 1e12)
    if peak and vit32_fps and vit_flops:
        mfu_vit32 = vit32_fps * (vit_flops / mb32) / (peak * 1e12)
    vit_bytes32 = vit_bytes[0]

    # BASELINE.md's bar is the PIPELINE number; lead with it when the
    # pipeline section produced one (raw invoke stays as its own field)
    if pipeline_fps is not None:
        metric, value = (
            "mobilenet_v2_224_pipeline_fps_per_chip", pipeline_fps
        )
    else:
        metric, value = "mobilenet_v2_224_bs1_fps_per_chip", fps
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": "fps",
                "vs_baseline": round(value / 1000.0, 3),
                "pipeline_fps": _round(pipeline_fps),
                "pipeline_p50_e2e_ms": _round(pipeline_p50_ms, 3),
                "pipeline_p95_e2e_ms": _round(pipeline_p95_ms, 3),
                "pipeline_p99_e2e_ms": _round(pipeline_p99_ms, 3),
                "pipeline_rate_p50_ms": _round(pipeline_rate_p50_ms, 3),
                "rate_drop_pct": rate_drop_pct,
                "pipeline_h2d_fps": _round(pipeline_h2d_fps),
                "pipeline_mb8_fps": _round(pipeline_mb8_fps),
                "pipeline_mb32_fps": _round(pipeline_mb32_fps),
                "pipeline_mb32_dev_fps": _round(pipeline_mb32_dev_fps),
                "pipeline_branched_fps": _round(pipeline_branched_fps),
                "pipeline_media_fps": _round(pipeline_media_fps),
                "executor_chain_fps": _round(executor_chain_fps),
                "executor_branched_fps": _round(executor_branched_fps),
                "chain_program_fps": _round(chain_program_fps),
                "chain_program_pernode_fps": _round(
                    chain_program_pernode_fps
                ),
                "chain_program_frac": (
                    round(chain_program_fps / chain_program_pernode_fps, 3)
                    if chain_program_fps and chain_program_pernode_fps
                    else None
                ),
                "executor_chain_fps_spread_pct": ceiling_spreads.get(
                    "executor_chain_fps"
                ),
                "executor_branched_fps_spread_pct": ceiling_spreads.get(
                    "executor_branched_fps"
                ),
                "chain_program_fps_spread_pct": ceiling_spreads.get(
                    "chain_program_fps"
                ),
                "overlap_efficiency": (
                    round(overlap_efficiency, 4)
                    if overlap_efficiency is not None else None
                ),
                "raw_invoke_bs1_fps": round(fps, 1),
                "p50_sync_latency_ms": round(p50, 3),
                "amortized_frame_ms": round(dt / iters * 1000, 3),
                "h2d_streaming_fps": round(h2d_fps, 1),
                "h2d_batched8_fps": _round(h2d_b8_fps),
                "microbatch8_fps": round(mb_fps, 1),
                "microbatch32_fps": _round(mb32_fps),
                "vit_mb32_fps": _round(vit32_fps),
                "int8_mb8_fps": _round(int8_fps),
                # which int8 configuration the cell measured: --gate
                # only compares int8_mb8_fps when the reference was
                # captured with the SAME configuration
                "int8_impl": "int8w",
                "composite_face_fps": _round(composite_fps),
                "composite_fused_fps": _round(fused_fps),
                "lm_decode_tok_s": _round(lm_tok_s),
                "lm_decode_int8w_tok_s": _round(lm_int8w_tok_s),
                "lm_decode_ngram_tok_s": _round(lm_ngram_tok_s),
                "lm_cb4_tok_s": _round(lm_cb_tok_s),
                "lm_cb4_spec_ngram_tok_s": _round(lm_cb_spec_ngram_tok_s),
                "lm_cb4_spec_draft_tok_s": _round(lm_cb_spec_draft_tok_s),
                "flops_per_frame": flops,
                "mfu_bs1": round(mfu, 4) if mfu is not None else None,
                "mfu_mb8": round(mfu8, 4) if mfu8 is not None else None,
                "mfu_mb32": round(mfu32, 4) if mfu32 is not None else None,
                "mfu_vit_mb32": (
                    round(mfu_vit32, 4) if mfu_vit32 is not None else None
                ),
                "mbv2_mb32_bytes_accessed": mbv2_bytes32,
                "vit_mb32_bytes_accessed": vit_bytes32,
                "platform": dev.platform,
                "device": str(dev.device_kind),
                # --gate only hard-fails against a same-host reference:
                # the executor ceilings are host-CPU numbers, and e.g.
                # the TPU relay host vs a CI container differ ~5×
                "host": _platform.node(),
            }
        )
    )


def _tunnel_alive():
    """Cheap liveness probe for the remote-accelerator relay. When the
    relay is dead the axon client retries connect forever and
    jax.devices() blocks indefinitely — burning every retry window.
    Returns None when the topology is unknown (don't gate)."""
    ips = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    if not ips:
        return None
    import socket

    hosts = [h.strip() for h in ips.split(",") if h.strip()]
    for attempt in range(3):
        for host in hosts:  # any live pool member counts
            try:
                socket.create_connection((host, 8082), timeout=2).close()
                return True
            except OSError:
                pass
        if attempt < 2:
            time.sleep(2)
    return False


def _probe() -> None:
    """Attach + one op + exit. Run as a short-timeout subprocess to test
    whether the TPU claim is obtainable at all before committing a full
    measurement window to it (a wedged claim blocks attach for tens of
    minutes; the relay TCP probe cannot see that)."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(jnp.zeros((8,), jnp.float32) + 1.0)
    print("probe-ok")


def _tpu_attachable(here: str, budget_s: float = 420.0) -> bool:
    """Repeatedly probe the TPU attach with short subprocess timeouts.
    True once a probe succeeds; False when the budget is spent."""
    import subprocess

    t0 = time.time()
    delay = 0.0
    while time.time() - t0 < budget_s:
        if delay:
            time.sleep(min(delay, max(0.0, budget_s - (time.time() - t0))))
        try:
            p = subprocess.run(
                [sys.executable, here, "--probe"],
                capture_output=True, text=True, timeout=90,
            )
            if p.returncode == 0 and "probe-ok" in p.stdout:
                return True
        except subprocess.TimeoutExpired:
            pass
        print("[bench] attach probe failed; backing off", file=sys.stderr)
        delay = 45.0
    return False


def _record_measured(line: str) -> None:
    """Persist a TPU-captured line as builder-attested evidence
    (BENCH_MEASURED_r04.json; override with BENCH_MEASURED_PATH). The
    driver snapshots BENCH_r{N}.json at round end, but live relay
    windows are rare — any successful TPU capture lands in the repo
    the moment it happens (VERDICT r3 #1)."""
    try:
        data = json.loads(line)
        if data.get("platform") != "tpu":
            return
        path = os.environ.get(
            "BENCH_MEASURED_PATH", "BENCH_MEASURED_r05.json"
        )
        here = os.path.dirname(os.path.abspath(__file__))
        full = os.path.join(here, path)
        # every TPU capture is appended next to the measured file
        # verbatim (evidence is never lost to the best-by-value policy
        # below; an overridden BENCH_MEASURED_PATH keeps its archive
        # beside it — test isolation)
        arch_dir = (
            os.path.join(here, "docs") if path == os.path.basename(path)
            else os.path.dirname(full)
        )
        try:
            os.makedirs(arch_dir, exist_ok=True)
            with open(
                os.path.join(arch_dir, "bench_captures_r05.jsonl"), "a"
            ) as f:
                f.write(json.dumps({"t": time.time(), **data}) + "\n")
        except Exception as exc:  # noqa: BLE001 — the archive is a
            # bonus; the measured file below must still be written
            print(f"[bench] capture archive failed: {exc!r}",
                  file=sys.stderr)
        # keep the BEST capture by headline value: relay throughput
        # varies ~20× between windows (docs/BENCH_NOTES.md cost model),
        # and a capture taken in a degraded window must not clobber
        # evidence from a healthy one. A regression must stay VISIBLE in
        # the primary artifact though, so the kept record always carries
        # a `last_run` summary of the newest capture plus a count of
        # lower captures discarded since the best one landed.
        last_run = {
            "t": time.time(),
            "value": data.get("value"),
            "partial": bool(data.get("partial")),
        }
        if os.path.exists(full):
            try:
                with open(full) as f:
                    prev = json.load(f)
                # a full record always replaces a partial; a partial
                # never replaces a full; otherwise best headline wins
                prev_partial = bool(prev.get("partial"))
                new_partial = bool(data.get("partial"))
                lower_value = (
                    prev_partial == new_partial
                    and float(prev.get("value") or 0)
                    > float(data.get("value") or 0)
                )
                keep_prev = (new_partial and not prev_partial) or lower_value
                if keep_prev:
                    prev["last_run"] = last_run
                    # evidence-stamp backfill (nns-kscope discipline):
                    # records kept from before the platform/device/host
                    # stamps existed gain them from the fresh capture
                    # (same process, same backend) without losing their
                    # better headline
                    for stamp in ("platform", "device", "host"):
                        if not prev.get(stamp) and data.get(stamp):
                            prev[stamp] = data[stamp]
                    if lower_value:
                        # counts only genuinely-lower same-kind captures —
                        # a partial discarded against a full record is
                        # not a regression signal
                        prev["discarded_lower_captures"] = (
                            int(prev.get("discarded_lower_captures") or 0)
                            + 1
                        )
                    with open(full, "w") as f:
                        json.dump(prev, f, indent=1)
                        f.write("\n")
                    print(
                        f"[bench] TPU capture kept: existing {path} has a "
                        "better/fuller record (last_run updated)",
                        file=sys.stderr,
                    )
                    return
            except Exception:  # noqa: BLE001 — unreadable prior: replace
                pass
        data["last_run"] = last_run
        with open(full, "w") as f:
            json.dump(data, f, indent=1)
            f.write("\n")
        print(f"[bench] TPU capture recorded to {path}", file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 — never lose the stdout line
        print(f"[bench] capture record failed: {exc!r}", file=sys.stderr)


def _relay_up(timeout: float = 3.0) -> bool:
    """One cheap TCP probe of the relay pool (no jax import — a dead
    relay makes jax.devices() block forever in the axon client's
    connect-retry loop). Shared implementation:
    platform_pin.probe_relay."""
    from nnstreamer_tpu.platform_pin import probe_relay

    return probe_relay(timeout=timeout)


def _watch() -> None:
    """Standing relay watcher (VERDICT r4 #1). The only live window ever
    observed lasted ~5 minutes; a 10-minute poll cadence can straddle and
    miss one entirely. This loop probes every <=45 s, appends every probe
    to docs/relay_probes_r05.log, and the instant the relay answers it
    fires the full capture ladder (all optional cells forced) which
    self-records BENCH_MEASURED_r05.json, then commits the evidence.
    Runs until BENCH_WATCH_DEADLINE_S expires (default 12 h)."""
    import subprocess

    here = os.path.abspath(__file__)
    repo = os.path.dirname(here)
    log_path = os.path.join(
        repo, "docs", os.environ.get("BENCH_WATCH_LOG", "relay_probes_r05.log")
    )
    deadline = time.time() + float(
        os.environ.get("BENCH_WATCH_DEADLINE_S", str(12 * 3600))
    )
    interval = float(os.environ.get("BENCH_WATCH_INTERVAL_S", "45"))
    captures = 0
    max_captures = int(os.environ.get("BENCH_WATCH_MAX_CAPTURES", "2"))

    def log(msg: str) -> None:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(log_path, "a") as f:
            f.write(f"{stamp} {msg}\n")

    log(f"watch-start interval={interval:.0f}s pid={os.getpid()}")
    while time.time() < deadline:
        up = _relay_up()
        log("alive" if up else "dead")
        if up and captures < max_captures:
            captures += 1
            log(f"capture-start attempt={captures}")
            env = dict(
                os.environ,
                BENCH_FORCE_OPTIONAL="1",
                BENCH_MEASURED_PATH="BENCH_MEASURED_r05.json",
            )
            try:
                p = subprocess.run(
                    [sys.executable, here],
                    capture_output=True, text=True, timeout=3000, env=env,
                )
                tail = (p.stdout.strip().splitlines() or [""])[-1][:400]
                log(f"capture-done rc={p.returncode} line={tail}")
            except subprocess.TimeoutExpired:
                log("capture-timeout after 3000s")
            measured = os.path.join(repo, "BENCH_MEASURED_r05.json")
            if os.path.exists(measured):
                try:
                    caps = os.path.join(
                        repo, "docs", "bench_captures_r05.jsonl"
                    )
                    subprocess.run(
                        ["git", "-C", repo, "add",
                         "BENCH_MEASURED_r05.json", log_path]
                        + ([caps] if os.path.exists(caps) else []),
                        check=True, capture_output=True, text=True,
                    )
                    subprocess.run(
                        ["git", "-C", repo, "commit", "-m",
                         "TPU capture: BENCH_MEASURED_r05.json (relay watcher)"],
                        check=True, capture_output=True, text=True,
                    )
                    log("capture-committed")
                except subprocess.CalledProcessError as exc:
                    log(f"capture-commit-failed {exc.stderr[-200:]}")
            else:
                log("capture-no-tpu-line (platform!=tpu or run failed)")
        time.sleep(interval)
    log("watch-deadline-reached")


def _executor_ceilings(runs: int = 3):
    """Executor-only fps ceilings: pipelines over host tensors measure
    what the executor itself — threads, channels, Frame plumbing, sync
    policies — costs per frame, i.e. the fps/core ceiling it imposes on
    any pipeline. Runs in a CPU-pinned subprocess so a TPU-attached
    bench process doesn't turn the trivial jit into a tunnel round-trip
    (and so the --gate numbers compare like-for-like with a TPU
    capture's). Chain = 3 nodes / 2 hops; branched = tee → 2 branches →
    mux(slowest) = 6 nodes / 7 hops + grouping (the multi-branch
    pressure case).

    MEDIAN of ``runs`` short captures, not one long one: a single
    capture swings ±30% on a noisy container — wider than the 25%
    --gate threshold, so one unlucky scheduler beat could fail (or one
    lucky one pass) the gate on noise alone. The per-key relative
    spread ((max−min)/median) rides along so records show how
    trustworthy each number is.

    The chain_program pair measures the SAME 3-stage chain (stages
    split by queues so they plan as three fused segments) both ways:
    compiled into one resident window program (chain_mode=auto, the
    one-launch-per-window path, docs/chain-analysis.md "Compiled
    chains") and per-node (chain_mode=off, one service thread per
    stage). Their ratio is the whole-chain compilation win with host
    speed cancelled — the acceptance bar is >= 1.5x.

    Returns ``(chain, branched, chain_prog, chain_pernode, spreads)``
    with ``spreads`` mapping gate key → spread percent (None when
    unmeasurable)."""
    import statistics
    import subprocess

    code = r"""
import os, time, jax
jax.config.update("jax_platforms", "cpu")
from nnstreamer_tpu.pipeline.parse import parse_pipeline
RUNS = %d
N = 8000
chain = (f"tensorsrc dimensions=4 num-frames={N} ! "
         "tensor_filter framework=passthrough ! tensor_sink sync-window=64")
branched = (f"tensorsrc dimensions=4 num-frames={N // 2} ! tee name=t "
            "t. ! queue ! tensor_filter framework=passthrough ! m.sink_0 "
            "t. ! queue ! tensor_filter framework=passthrough ! m.sink_1 "
            "tensor_mux name=m sync-mode=slowest ! tensor_sink "
            "sync-window=64")
prog = (f"tensorsrc dimensions=4 num-frames={N} ! "
        "tensor_filter framework=passthrough ! queue ! "
        "tensor_filter framework=passthrough ! queue ! "
        "tensor_filter framework=passthrough ! tensor_sink sync-window=64")
for _ in range(RUNS):
    for label, desc, n, mode in (("chain", chain, N, None),
                                 ("branched", branched, N // 2, None),
                                 ("chain_program", prog, N, "auto"),
                                 ("chain_pernode", prog, N, "off")):
        if mode is None:
            os.environ.pop("NNS_TPU_EXECUTOR_CHAIN_MODE", None)
            os.environ.pop("NNS_TPU_EXECUTOR_CHAIN_UNROLL", None)
        else:
            os.environ["NNS_TPU_EXECUTOR_CHAIN_MODE"] = mode
            os.environ["NNS_TPU_EXECUTOR_CHAIN_UNROLL"] = "32"
        p = parse_pipeline(desc)
        t0 = time.perf_counter()
        p.run(timeout=600)
        print(f"{label} {n / (time.perf_counter() - t0):.1f}")
""" % max(1, int(runs))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=900, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    vals = {"chain": [], "branched": [], "chain_program": [],
            "chain_pernode": []}
    for line in out.stdout.splitlines():
        bits = line.split()
        if len(bits) == 2 and bits[0] in vals:
            vals[bits[0]].append(float(bits[1]))

    def _median_spread(xs):
        if not xs:
            return None, None
        med = statistics.median(xs)
        spread = (
            100.0 * (max(xs) - min(xs)) / med if med > 0 and len(xs) > 1
            else 0.0
        )
        return med, round(spread, 1)

    chain, chain_spread = _median_spread(vals["chain"])
    branched, branched_spread = _median_spread(vals["branched"])
    chain_prog, prog_spread = _median_spread(vals["chain_program"])
    chain_pernode, pernode_spread = _median_spread(vals["chain_pernode"])
    return chain, branched, chain_prog, chain_pernode, {
        "executor_chain_fps": chain_spread,
        "executor_branched_fps": branched_spread,
        "chain_program_fps": prog_spread,
        "chain_program_pernode_fps": pernode_spread,
    }


def _overlap_efficiency():
    """Fused-segment overlap efficiency: fraction of the segment's
    steady-state wall window covered by its in-flight frame spans.
    Tracer complete events on a ringed FusedNode span dequeue→delivery,
    so with the double-buffer ring healthy the union of spans tiles the
    wall densely; per-frame dead time the ring can't hide — channel
    waits, stat/metrics indirection, delivery stalls — opens gaps and
    drags the number down. Runs in a CPU-pinned subprocess like
    _executor_ceilings so --gate needs no relay window."""
    import subprocess

    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from nnstreamer_tpu import trace
from nnstreamer_tpu.pipeline.parse import parse_pipeline
N = 4000
desc = (f"tensorsrc dimensions=64:64 num-frames={N} ! "
        "tensor_transform mode=arithmetic option=add:1.0 ! "
        "tensor_sink sync-window=64")
tracer = trace.enable()
tracer.clear()
p = parse_pipeline(desc)
p.run(timeout=600)
spans = sorted(
    (ev["ts"], ev["ts"] + ev["dur"])
    for ev in tracer.events()
    if ev.get("cat") == "FusedNode" and ev.get("ph") == "X"
)
# steady state only: the head holds the jit compile + warmup stalls
spans = spans[len(spans) // 10:]
if len(spans) > 1:
    wall = spans[-1][1] - spans[0][0]
    covered = 0.0
    cur_s, cur_e = spans[0]
    for s, e in spans[1:]:
        if s > cur_e:
            covered += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    covered += cur_e - cur_s
    if wall > 0:
        print(f"overlap {covered / wall:.4f}")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=900, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.splitlines():
        bits = line.split()
        if len(bits) == 2 and bits[0] == "overlap":
            return float(bits[1])
    return None


def _composite_face_cell() -> float | None:
    """Fresh composite_face_fps measurement for --gate: the same
    device-crop element cascade + methodology as _run's composite cell
    (warm run, then wall-clock n/(t) on the measured run). Runs on
    whatever backend the host attaches — the reference capture's
    environment — so same-host comparisons compare like with like."""
    import jax

    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    on_tpu = jax.devices()[0].platform == "tpu"

    def once(n: int) -> float:
        desc = (
            f"videotestsrc pattern=gradient num-frames={n} "
            f"device={'true' if on_tpu else 'false'} "
            "width=128 height=128 ! "
            "tensor_converter ! tee name=t "
            "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
            'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
            "crop.sink_1 "
            "t. ! queue ! crop.sink_0 "
            "tensor_crop name=crop out-size=112:112 max-crops=16 ! "
            "tensor_filter framework=jax model=zoo:face_landmark "
            'custom="batch:16" ! fakesink sync-window=16'
        )
        p = parse_pipeline(desc)
        t = time.perf_counter()
        p.run(timeout=600)
        return n / (time.perf_counter() - t)

    once(2)
    return once(128 if on_tpu else 8)


def _int8_mb8_cell() -> float | None:
    """Fresh int8_mb8_fps measurement for --gate: the end-to-end
    quantized path (quantize=int8w, fused dequant epilogue) at
    microbatch 8, same loop shape as _run's int8 cell."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.models import zoo

    on_tpu = jax.devices()[0].platform == "tpu"
    mb = 8
    rng = np.random.default_rng(0)
    frames = [
        jnp.asarray(rng.integers(0, 255, (mb, 224, 224, 3), np.uint8))
        for _ in range(4)
    ]
    m = zoo.get(
        "mobilenet_v2", quantize="int8w", batch=str(mb),
        compute_dtype="bfloat16",
    )
    fn = jax.jit(m.fn)
    jax.block_until_ready(fn(frames[0]))
    iters = 256 if on_tpu else 8
    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        out = fn(frames[i % 4])
        if (i + 1) % 64 == 0:
            out.block_until_ready()
    out.block_until_ready()
    return iters * mb / (time.perf_counter() - t0)


def _paged_tok_frac_cell() -> float | None:
    """Fresh paged_tok_frac measurement for --gate: paged (block-native
    default) decode tok/s over slot-layout tok/s at EQUAL occupancy —
    the `--pipeline llm` parity cell's ratio, measured lean (no
    capacity sweep). A ratio, so host speed largely cancels; a drop
    means the block-native decode path itself regressed vs the slot
    step (e.g. a reintroduced gather/scatter or view carry)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    rng = np.random.default_rng(0)
    if on_tpu:
        model_kw = dict(vocab=32000, d_model=512, n_heads=8, n_layers=4)
        dtype = jnp.bfloat16
    else:
        model_kw = dict(vocab=512, d_model=64, n_heads=4, n_layers=2)
        dtype = jnp.float32
    params = tfm.init_params(jax.random.PRNGKey(7), **model_kw)
    max_len, prompt_len, block_size = 192, 32, 16
    slots, tok_budget = 6, 64
    prompts = [
        rng.integers(1, model_kw["vocab"], (48,)).astype(np.int32)
        for _ in range(slots)
    ]

    def _mk(layout):
        kw = dict(compute_dtype=dtype)
        if layout == "paged":
            kw.update(kv_layout="paged", block_size=block_size,
                      kv_blocks=slots * max_len // block_size)
        return ContinuousBatcher(
            params, model_kw["n_heads"], n_slots=slots, max_len=max_len,
            prompt_len=prompt_len, **kw,
        )

    slot_tok_s = _llm_equal_occupancy_tok_s(_mk("slot"), prompts, tok_budget)
    paged_tok_s = _llm_equal_occupancy_tok_s(
        _mk("paged"), prompts, tok_budget
    )
    if not slot_tok_s:
        return None
    return round(paged_tok_s / slot_tok_s, 3)


def _plane_async_frac_cell() -> float | None:
    """Fresh plane_async_frac measurement for --gate: async
    (ring-depth=3 ticket rings) over blocking aggregate fps, 8
    latency-shaped streams (max-batch=2 local windows) through one
    shared plane on the weight-bound MLP — the `--pipeline plane`
    async cell pair, measured lean. A ratio, so host speed cancels; a
    drop means the async submit path itself regressed (a reintroduced
    block on the stream service thread, a ring that stopped engaging,
    or a scheduler change that re-convoys the dispatches)."""
    model = _plane_mlp_model()
    n_streams, n_frames = 8, 240
    async_fps, _, _ = _plane_run_streams(
        model, n_streams, n_frames,
        "plane=gate_async plane-max-batch=32 plane-timeout-ms=2 "
        "max-batch=2 ring-depth=3",
    )
    sync_fps, _, _ = _plane_run_streams(
        model, n_streams, n_frames,
        "plane=gate_sync plane-max-batch=32 plane-timeout-ms=2 "
        "max-batch=2",
    )
    if not sync_fps:
        return None
    return round(async_fps / sync_fps, 3)


def _llm_equal_occupancy_tok_s(cb, prompts, budget: int) -> float:
    """Decode tok/s at EQUAL occupancy — the one methodology behind
    ``paged_tok_frac`` (`--pipeline llm` and `--gate`).

    A warm submit→drain round compiles every program the measured
    round will touch (including the paged prefix-hit admission path,
    which only engages on a resubmitted prompt); the measured round
    then pumps until every request is ADMITTED before the clock
    starts — occupancy is only equal once it is full on both layouts
    (the slot layout admits synchronously in submit(); paged trickles
    chunked prefill through the pumps, an admission-latency policy the
    capacity/TTFT cells already account). Tokens are counted from the
    pump returns, so partial decoding during admission cancels out."""
    for _ in range(2):  # second round warms the prefix-hit admission
        rids = [cb.submit(p, budget) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            cb.step_pump(8)
    rids = [cb.submit(p, budget) for p in prompts]
    while cb.stats().get("kv_prefill_queue", 0) > 0:
        cb.step_pump(1)
    cb.step_pump(1)  # apply the last pending activation
    n = 0
    t0 = time.perf_counter()
    while any(cb.result(r) is None for r in rids):
        out = cb.step_pump(8)
        n += sum(len(v) for v in out.values())
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else 0.0


# --gate compares these keys; the executor ceilings + overlap are
# measurable on a CPU-pinned host so the gate needs no relay window;
# the composite/int8/paged cells measure on whatever backend attaches
# (the reference environment) and are gated only when the reference
# record carries them — older references skip them until next capture
# (`bench.py --capture-measured` writes one with every gated cell).
# Thresholds are per-key fractions of allowed drop vs the reference.
GATE_KEYS = {
    "executor_chain_fps": 0.25,
    "executor_branched_fps": 0.25,
    "overlap_efficiency": 0.25,
    # element-cascade cell: includes compile in its wall window, so a
    # loaded host wobbles it more than the paced ceilings
    "composite_face_fps": 0.3,
    "int8_mb8_fps": 0.25,
    # paged/slot decode tok/s ratio at equal occupancy: host speed
    # cancels in the ratio (measured ~1.5-1.7 on the CPU smoke — the
    # block-native pump beats the slot layout's) — a breach means the
    # block-native decode path itself regressed, e.g. a reintroduced
    # gather/scatter or view carry
    "paged_tok_frac": 0.2,
    # async/blocking plane submit fps ratio at 8 latency-shaped
    # streams: host speed cancels in the ratio (~1.6 on the CPU smoke
    # vs the 1.3 acceptance bar) — a breach means blocking crept back
    # into the stream-side submit path or the in-flight ring stopped
    # filling dispatches
    "plane_async_frac": 0.2,
    # compiled whole-chain window program ceiling (one XLA launch per
    # unrolled window — pipeline/chain_program.py); absolute fps rides
    # the host like the other ceilings
    "chain_program_fps": 0.25,
    # compiled/per-node fps ratio on the SAME 3-stage chain: host speed
    # cancels in the ratio (measured ~1.6-2x on the CPU smoke vs the
    # 1.5 acceptance bar) — a breach means per-frame work crept back
    # into the window path (meta hops, per-frame dispatch, ring stalls)
    "chain_program_frac": 0.2,
}

# fresh in-process measurements for the backend-dependent cells —
# _gate and --capture-measured iterate this SAME tuple, so a new cell
# cannot land in one and silently vanish from the other (the gate
# skips keys the reference lacks without erroring)
GATED_CELLS = (
    ("composite_face_fps", _composite_face_cell),
    ("int8_mb8_fps", _int8_mb8_cell),
    ("paged_tok_frac", _paged_tok_frac_cell),
    ("plane_async_frac", _plane_async_frac_cell),
)

# cells whose headline is pallas-labelled: on a TPU capture their
# dispatch-tally evidence (--capture-tpu `cells.<key>.dispatch`) should
# show these ops engaging the pallas path. --gate WARNS on stderr (never
# fails — the number is still a real measurement) when the reference
# evidence shows only the fallback engaged: the cell measured the jnp
# path while its label claims the kernel.
PALLAS_CELLS = {
    "composite_face_fps": ("crop_and_resize",),
}


def _pallas_tally_warnings(ref: dict) -> list:
    """Warnings for pallas-labelled cells whose TPU evidence record
    shows the fallback engaged instead of the kernel. Pure — reads only
    the record (tests feed synthetic ones)."""
    out = []
    if str(ref.get("platform")) != "tpu":
        return out  # CPU references legitimately run the jnp path
    cells = ref.get("cells") or {}
    for key, ops in PALLAS_CELLS.items():
        disp = (cells.get(key) or {}).get("dispatch") or {}
        if not disp:
            continue  # pre-capture-tpu reference: no evidence either way
        for op in ops:
            pallas_n = disp.get(f"{op}:pallas", 0)
            other = {
                k: n for k, n in disp.items()
                if k.startswith(f"{op}:") and not k.endswith(":pallas")
            }
            if other and not pallas_n:
                out.append(
                    f"[gate] {key}: TPU evidence shows {op} dispatched "
                    f"only the fallback ({other}) — the pallas-labelled "
                    "cell measured the jnp path (nns-kscope --engage "
                    "diagnoses why)"
                )
    return out


def _gate_reference(argv) -> tuple[str, dict] | tuple[None, None]:
    """Resolve the reference record: an explicit path after --gate, or
    BENCH_MEASURED_PATH, or the newest BENCH_MEASURED_*.json beside
    this file (highest round number wins, mtime breaks ties)."""
    here = os.path.dirname(os.path.abspath(__file__))
    tail = argv[argv.index("--gate") + 1:][:1]
    if tail and not tail[0].startswith("-"):
        # explicit path: caller-relative (CWD), like any CLI file arg
        paths = [os.path.abspath(tail[0])]
    elif os.environ.get("BENCH_MEASURED_PATH"):
        paths = [os.path.abspath(os.environ["BENCH_MEASURED_PATH"])]
    else:
        import glob
        import re

        def _key(p):
            m = re.search(r"_r(\d+)\.json$", p)
            return (int(m.group(1)) if m else -1, os.path.getmtime(p))

        paths = sorted(
            glob.glob(os.path.join(here, "BENCH_MEASURED_*.json")),
            key=_key, reverse=True,
        )
    for p in paths:
        try:
            with open(p) as f:
                return os.path.basename(p), json.load(f)
        except Exception as exc:  # noqa: BLE001 — try the next candidate
            print(f"[gate] unreadable reference {p}: {exc!r}",
                  file=sys.stderr)
    return None, None


def _gate() -> int:
    """Bench regression gate: re-measure the host-side executor
    ceilings and fail (exit 1) when any gated metric has regressed more
    than the allowed fraction vs the last measured capture — so a slide
    like r04→r05's executor_chain_fps ~21k→13.5k can't land silently.
    Exit 0 on pass, 2 when no reference/measurement is available
    (a missing baseline is a setup problem, not a regression).

    The gated ceilings are host-CPU numbers, so a floor breach is only
    a hard fail (exit 1) when the reference was captured on THIS host —
    against a foreign/unstamped reference (TPU relay host vs a CI
    container differ ~5× on raw fps) a breach reports
    ``stale-reference`` and exits 2 so cross-host runs can't
    false-fail. BENCH_GATE_FORCE=1 hard-compares anyway;
    BENCH_GATE_PCT overrides the allowed drop for every key."""
    ref_name, ref = _gate_reference(sys.argv)
    if not ref:
        print(json.dumps({"gate": "skip",
                          "reason": "no readable BENCH_MEASURED reference"}))
        return 2
    same_host = (
        ref.get("host") == _platform.node()
        or os.environ.get("BENCH_GATE_FORCE") == "1"
    )
    for w in _pallas_tally_warnings(ref):
        print(w, file=sys.stderr)
    try:
        chain, branched, chain_prog, chain_pernode, spreads = (
            _executor_ceilings()
        )
    except Exception as exc:  # noqa: BLE001 — a gate that cannot
        # measure must not masquerade as a pass
        print(json.dumps({"gate": "error", "reason": repr(exc)}))
        return 2
    overlap = None
    if ref.get("overlap_efficiency"):
        # measured (and gated) only when the reference carries the key;
        # pre-PR-8 references don't, and measuring an ungated metric
        # would just burn a subprocess
        try:
            overlap = _overlap_efficiency()
        except Exception as exc:  # noqa: BLE001
            print(f"[gate] overlap measurement failed: {exc!r}",
                  file=sys.stderr)
        if overlap is None:
            # same rule as the ceilings: a gated key that cannot be
            # measured must not masquerade as a pass — the overlap
            # ceiling would otherwise self-disable on the first
            # measurement failure
            print(json.dumps({"gate": "error",
                              "reason": "overlap_efficiency unmeasurable"}))
            return 2
    failures, checked, skipped = [], {}, []
    fresh = {
        "executor_chain_fps": chain,
        "executor_branched_fps": branched,
        "chain_program_fps": chain_prog,
        "chain_program_frac": (
            round(chain_prog / chain_pernode, 3)
            if chain_prog and chain_pernode else None
        ),
        "overlap_efficiency": overlap,
    }
    for key, cell in GATED_CELLS:
        # composite_face_fps predates this gate key with UNCHANGED
        # methodology (the shared _composite_face_cell), so pre-PR-12
        # references gate it meaningfully; int8_mb8_fps changed
        # configuration and waits for the int8_impl stamp below
        if not ref.get(key):
            continue  # reference lacks the cell: skipped
        if key == "int8_mb8_fps" and ref.get("int8_impl") != "int8w":
            # the cell's configuration changed (activation-quant int8 →
            # weight-only int8w in PR 12): comparing across
            # configurations would gate apples against oranges — wait
            # for a reference captured with the new path (the record
            # stamps int8_impl)
            continue
        if not same_host:
            # these cells ride the capture backend (TPU on a relay
            # capture): cross-host they can only produce a
            # stale-reference verdict — don't burn minutes measuring
            # it (the compare loop reports the key as skipped)
            continue
        got = None
        try:
            got = cell()
        except Exception as exc:  # noqa: BLE001
            print(f"[gate] {key} measurement failed: {exc!r}",
                  file=sys.stderr)
        if got is None:
            # same rule as the overlap ceiling: a gated key that cannot
            # be measured must not masquerade as a pass
            print(json.dumps({"gate": "error",
                              "reason": f"{key} unmeasurable"}))
            return 2
        fresh[key] = got
    override = None
    raw_pct = os.environ.get("BENCH_GATE_PCT")
    if raw_pct:
        try:
            override = float(raw_pct)
        except ValueError:
            print(json.dumps({
                "gate": "error",
                "reason": f"BENCH_GATE_PCT={raw_pct!r} is not a number",
            }))
            return 2
        if override > 1.0:
            # the name says percent: 25 means "allow a 25% drop", not a
            # 2500% one (which would disable the gate silently)
            override /= 100.0
    for key, allowed in GATE_KEYS.items():
        if override is not None:
            allowed = override
        ref_v, new_v = ref.get(key), fresh.get(key)
        if not ref_v or not new_v:  # absent/null/0 on either side
            skipped.append(key)
            continue
        floor = float(ref_v) * (1.0 - allowed)
        checked[key] = {
            "reference": _round(float(ref_v)), "measured": _round(new_v),
            "floor": _round(floor),
            "delta_pct": _round(100.0 * (new_v - float(ref_v)) / float(ref_v)),
            # median-of-3 relative spread: how much of the delta is
            # plain measurement noise on this container
            "spread_pct": spreads.get(key),
        }
        if new_v < floor:
            failures.append(key)
    if not checked:
        print(json.dumps({"gate": "skip", "reference": ref_name,
                          "reason": "no gated key present in both records",
                          "skipped": skipped}))
        return 2
    status = "pass"
    if failures:
        status = "fail" if same_host else "stale-reference"
    print(json.dumps({
        "gate": status,
        "reference": ref_name,
        "reference_host": ref.get("host"),
        "host": _platform.node(),
        "failed": failures,
        "checked": checked,
        "skipped": skipped,
    }, indent=1))
    return (1 if same_host else 2) if failures else 0


def _capture_measured() -> int:
    """``--capture-measured <path>``: measure every gated cell fresh on
    THIS host and write a BENCH_MEASURED-style reference record, so the
    gate keys added since the last full relay capture
    (overlap_efficiency, composite_face_fps, int8_mb8_fps,
    paged_tok_frac) stop being skipped for lack of a reference. The
    record stamps ``host`` (the gate's same-host rule) and
    ``int8_impl`` (the int8 cell's configuration guard). Never run
    concurrently with a tier-1 measurement."""
    import jax

    tail = sys.argv[sys.argv.index("--capture-measured") + 1:][:1]
    if not tail or tail[0].startswith("-"):
        print("usage: bench.py --capture-measured <out.json>",
              file=sys.stderr)
        return 2
    path = os.path.abspath(tail[0])
    rec = {
        "metric": "bench_gate_reference_capture",
        "host": _platform.node(),
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0].device_kind),
        "int8_impl": "int8w",
    }
    _mark("capture start")
    chain, branched, chain_prog, chain_pernode, spreads = (
        _executor_ceilings()
    )
    rec["executor_chain_fps"] = _round(chain)
    rec["executor_branched_fps"] = _round(branched)
    rec["chain_program_fps"] = _round(chain_prog)
    rec["chain_program_pernode_fps"] = _round(chain_pernode)
    rec["chain_program_frac"] = (
        round(chain_prog / chain_pernode, 3)
        if chain_prog and chain_pernode else None
    )
    for key, spread in spreads.items():
        rec[f"{key}_spread_pct"] = spread
    _mark("executor ceilings")
    for key, cell in (
        ("overlap_efficiency", _overlap_efficiency),
    ) + GATED_CELLS:
        try:
            rec[key] = _round(cell(), 4)
        except Exception as exc:  # noqa: BLE001 — capture what measures;
            # the gate skips keys absent from the reference
            print(f"[capture] {key} failed: {exc!r}", file=sys.stderr)
            rec[key] = None
        _mark(key)
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))
    return 0


def _capture_tpu() -> int:
    """``--capture-tpu <out.json>``: TPU-evidence capture (nns-kscope
    discipline, docs/kernel-analysis.md). The record carries the
    platform/device fingerprint, every gated cell measured with a
    dispatch-tally diff beside its value (which implementation each
    dual-path op engaged WHILE the cell ran — the per-cell proof the
    pallas label claims), and the kernel engage rows (tiny probes with
    pallas explicitly requested). Exit 1 when any requested pallas path
    fell back. Never run concurrently with a tier-1 measurement."""
    import jax

    from nnstreamer_tpu.ops import dispatch

    tail = sys.argv[sys.argv.index("--capture-tpu") + 1:][:1]
    if not tail or tail[0].startswith("-"):
        print("usage: bench.py --capture-tpu <out.json>", file=sys.stderr)
        return 2
    path = os.path.abspath(tail[0])
    dev = jax.devices()[0]
    rec = {
        "metric": "bench_tpu_evidence_capture",
        "host": _platform.node(),
        "platform": dev.platform,
        "device": str(dev.device_kind),
        "n_devices": jax.device_count(),
        "int8_impl": "int8w",
        "cells": {},
    }
    _mark("capture-tpu start")
    for key, cell in GATED_CELLS:
        snap = dispatch.tally.snapshot()
        entry = {"value": None, "dispatch": {}}
        try:
            entry["value"] = _round(cell(), 4)
        except Exception as exc:  # noqa: BLE001 — capture what measures
            print(f"[capture-tpu] {key} failed: {exc!r}", file=sys.stderr)
            entry["error"] = repr(exc)
        now = dispatch.tally.snapshot()
        for (op, impl), n in sorted(now.items()):
            fresh_n = n - snap.get((op, impl), 0)
            if fresh_n > 0:
                entry["dispatch"][f"{op}:{impl}"] = fresh_n
        rec["cells"][key] = entry
        _mark(key)
    from nnstreamer_tpu.analysis.kernels import engage

    rec["kernels"] = engage()
    _mark("kernel engage probes")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))
    return 0 if all(r["ok"] for r in rec["kernels"]) else 1


def _pipeline_batched(smoke: bool) -> None:
    """``--pipeline batched``: micro-batched vs per-frame pipeline FPS
    (pipeline/batching.py), ONE JSON line. ``--smoke`` pins CPU and
    shrinks the MobileNet-style config so it runs inside tier-1: small
    spatial size (per-frame dispatch + executor overhead dominates, which
    is exactly what micro-batching amortizes — the CPU-visible share of
    the TPU story) and a small frame count."""
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    size = 224 if on_tpu else 32
    width = 1.0 if on_tpu else 0.25
    n_frames = 4096 if on_tpu else 256
    max_batch = 8

    from nnstreamer_tpu.pipeline.executor import FusedNode
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    def run_once(batching: bool):
        batch_props = (
            f"batching=true max-batch={max_batch} batch-timeout-ms=2"
            if batching else "batching=false"
        )
        desc = (
            f"videotestsrc pattern=gradient device=true "
            f"num-frames={n_frames} width={size} height={size} ! "
            "tensor_converter queue-size=128 ! "
            "tensor_filter framework=jax model=zoo:mobilenet_v2 "
            f'custom="size:{size},width:{width}" {batch_props} ! '
            "tensor_decoder mode=image_labeling ! "
            "tensor_sink sync-window=8 queue-size=128"
        )
        p = parse_pipeline(desc)
        ex = p.run(timeout=900)
        fps = _steady_fps(ex)
        seg = next(
            (n.seg for n in ex.nodes if isinstance(n, FusedNode)), None
        )
        return fps, seg

    unbatched_fps, _ = run_once(False)
    _mark("pipeline unbatched measured")
    batched_fps, seg = run_once(True)
    _mark("pipeline batched measured")
    speedup = (
        round(batched_fps / unbatched_fps, 3)
        if batched_fps and unbatched_fps else None
    )
    rec = {
        "metric": "mobilenet_style_pipeline_batched_vs_unbatched_fps",
        "unit": "fps",
        "batched_fps": _round(batched_fps),
        "unbatched_fps": _round(unbatched_fps),
        "speedup": speedup,
        "max_batch": max_batch,
        "size": size,
        "n_frames": n_frames,
        "platform": dev.platform,
        "device": str(dev.device_kind),
    }
    if seg is not None:
        rec.update(seg.batch_stats.snapshot())
        rec["segment_traces"] = seg.n_traces
    print(json.dumps(rec))


def _plane_mlp_model(d_in: int = 512, d_hid: int = 4096) -> str:
    """Write the weight-bound MLP (512→4096→512, ~16 MB of weights) the
    plane cells share: the serving-shaped regime where per-frame cost is
    dominated by streaming the weights, so batching K frames amortizes
    the weight traffic K× — the same shape continuous-batched LLM
    decode lives in."""
    import tempfile

    model_dir = tempfile.mkdtemp(prefix="nns_plane_bench_")
    model = os.path.join(model_dir, "mlp.py")
    with open(model, "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "_r = np.random.default_rng(0)\n"
            f"_W1 = jnp.asarray(_r.standard_normal(({d_in}, {d_hid}),"
            " np.float32) * 0.02)\n"
            f"_W2 = jnp.asarray(_r.standard_normal(({d_hid}, {d_in}),"
            " np.float32) * 0.02)\n"
            "def get_model(options):\n"
            "    return (lambda x: jnp.tanh(jnp.tanh(x @ _W1) @ _W2)),"
            " None\n"
        )
    return model


def _plane_run_streams(
    model: str, n_streams: int, n_frames: int, plane_props: str,
    d_in: int = 512,
):
    """All N pipelines concurrently; returns (sum of per-stream steady
    fps, per-stream list, one executor's plane stats) — shared by
    ``--pipeline plane`` and the ``plane_async_frac`` gate cell."""
    import threading

    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    descs = [
        (
            f"tensorsrc dimensions={d_in} types=float32 "
            f"pattern=random num-frames={n_frames} ! "
            f"tensor_filter framework=jax model={model} "
            f"input={d_in} inputtype=float32 {plane_props} ! "
            "tensor_sink sync-window=8 queue-size=128"
        )
        for _ in range(n_streams)
    ]
    pipelines = [parse_pipeline(d) for d in descs]
    execs = [None] * n_streams
    errors = []

    def drive(i: int) -> None:
        try:
            execs[i] = pipelines[i].run(timeout=900)
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append((i, exc))

    threads = [
        threading.Thread(target=drive, args=(i,))
        for i in range(n_streams)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"stream failures: {errors!r}")
    per_stream = [_steady_fps(ex) or 0.0 for ex in execs]
    plane_row = {}
    for ex in execs:
        for row in ex.stats().values():
            if "plane_name" in row:
                plane_row = {
                    k: v for k, v in row.items()
                    if k.startswith("plane_")
                    and k != "plane_per_stream"
                }
                break
        if plane_row:
            break
    return sum(per_stream), per_stream, plane_row


def _pipeline_plane(smoke: bool) -> None:
    """``--pipeline plane``: N concurrent client streams through ONE
    shared serving plane (serving_plane/, docs/serving-plane.md) vs the
    same N streams through isolated per-stream executors at equal
    device budget, ONE JSON line. The isolated baseline opens N
    backends (N weight copies) and dispatches N per-frame programs; the
    plane opens ONE and continuously batches across streams — the
    acceptance bar is aggregate plane throughput ≥ 1.5× isolated.

    A second cell pair measures ASYNC submits (ring-depth=3 ticket
    rings, docs/serving-plane.md) against blocking submits at equal
    config: LATENCY-SHAPED streams — small local windows
    (``max-batch=2``), so no client's frame parks in a deep local
    collector. Blocking submits then convoy: all 8 streams wait on one
    dispatch, the plane's queue empties every cycle, and each dispatch
    pays the straggler wait at partial occupancy (~11/32 measured).
    The async rings keep ~3 windows per stream in flight, so dispatches
    stay full (~31/32) with no straggler stalls — ``plane_async_frac``
    (async/blocking aggregate fps, the ``--gate`` key; bar ≥ 1.3×,
    ~1.6× measured on the CPU smoke). ``--smoke`` pins CPU and shrinks
    the run."""
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_streams = 8
    n_frames = 300 if smoke else (1500 if on_tpu else 600)
    model = _plane_mlp_model()

    iso_fps, iso_each, _ = _plane_run_streams(
        model, n_streams, n_frames, ""
    )
    _mark("isolated streams measured")
    # async measured BEFORE its blocking comparator so any second-run
    # jit/cache warmth favors the baseline, never the claimed win
    async_fps, async_each, async_row = _plane_run_streams(
        model, n_streams, n_frames,
        "plane=bench_async plane-max-batch=32 plane-timeout-ms=2 "
        "max-batch=2 ring-depth=3",
    )
    _mark("async plane streams measured")
    sync_fps, _sync_each, sync_row = _plane_run_streams(
        model, n_streams, n_frames,
        "plane=bench_sync plane-max-batch=32 plane-timeout-ms=2 "
        "max-batch=2",
    )
    _mark("blocking comparator measured")
    plane_fps, plane_each, plane_row = _plane_run_streams(
        model, n_streams, n_frames,
        "plane=bench plane-max-batch=32 plane-timeout-ms=2"
    )
    _mark("plane streams measured")
    speedup = (
        round(plane_fps / iso_fps, 3) if plane_fps and iso_fps else None
    )
    rec = {
        "metric": "plane_8stream_aggregate_vs_isolated_fps",
        "unit": "fps",
        "n_streams": n_streams,
        "n_frames_per_stream": n_frames,
        "plane_aggregate_fps": _round(plane_fps),
        "isolated_aggregate_fps": _round(iso_fps),
        "speedup": speedup,
        "plane_stream_min_fps": _round(min(plane_each) if plane_each else None),
        "isolated_stream_min_fps": _round(min(iso_each) if iso_each else None),
        # async-vs-blocking cell pair (max-batch=2 latency-shaped
        # windows, ring-depth=3): the gate key is the ratio so host
        # speed cancels
        "plane_async_aggregate_fps": _round(async_fps),
        "plane_blocking_aggregate_fps": _round(sync_fps),
        "plane_async_frac": (
            round(async_fps / sync_fps, 3)
            if async_fps and sync_fps else None
        ),
        "plane_async_stream_min_fps": _round(
            min(async_each) if async_each else None
        ),
        "plane_async_inflight_ring": 3,
        "plane_async_avg_batch": async_row.get("plane_avg_batch"),
        "plane_blocking_avg_batch": sync_row.get("plane_avg_batch"),
        "platform": dev.platform,
        "device": str(dev.device_kind),
        "host": _platform.node(),
    }
    rec.update(plane_row)
    print(json.dumps(rec))


def _pipeline_composite(smoke: bool) -> None:
    """``--pipeline composite``: the detect→crop→landmark cascade as
    FUSED device segments (face_detect output=regions+image →
    tensor_transform mode=crop-resize → landmark; zero host hops, the
    PR-8 resident handoff across the queue) vs the HOST-HOP form the
    reference builds (tensor_crop host path: variable-size crops
    materialize on host every frame, landmark re-invokes per shape),
    ONE JSON line. The device-crop element cascade (tensor_crop
    out-size=, the main record's composite_face_fps cell) is recorded
    beside them as the intermediate rung. Acceptance bar: fused ≥ 2×
    host-hop on the CPU smoke, with zero D2H bytes between the
    detector and landmark segments (also pinned by
    tests/test_ops_device.py). ``--smoke`` pins CPU; never run
    concurrently with a tier-1 measurement."""
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_frames = 256 if on_tpu else 64

    host_hop = (
        "videotestsrc pattern=gradient num-frames={n} width=128 "
        "height=128 ! tensor_converter ! tee name=t "
        "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
        "crop.sink_1 t. ! queue ! crop.sink_0 "
        "tensor_crop name=crop ! "
        "tensor_filter framework=jax model=zoo:face_landmark "
        'custom="" invoke-dynamic=true input-combination=0 ! fakesink'
    )
    device_crop = (
        "videotestsrc pattern=gradient num-frames={n} device=true "
        "width=128 height=128 ! tensor_converter ! tee name=t "
        "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
        "crop.sink_1 t. ! queue ! crop.sink_0 "
        "tensor_crop name=crop out-size=112:112 max-crops=16 ! "
        "tensor_filter framework=jax model=zoo:face_landmark "
        'custom="batch:16" ! fakesink sync-window=16'
    )
    fused = (
        "videotestsrc pattern=gradient num-frames={n} device=true "
        "width=128 height=128 ! tensor_converter ! "
        "tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions+image,threshold:0.0,frame_size:128:128" ! '
        "tensor_transform mode=crop-resize option=112:112 ! queue ! "
        "tensor_filter framework=jax model=zoo:face_landmark "
        'custom="batch:16" ! fakesink sync-window=16'
    )

    def run(desc, n=n_frames):
        p = parse_pipeline(desc.format(n=n))
        ex = p.run(timeout=900)
        return _steady_fps(ex), ex.transfer_totals()

    # every cell reports STEADY-STATE sink fps (_steady_fps: frames
    # after the first completed render burst — compiles and warmup
    # excluded), so the shorter host-hop run costs resolution, not
    # bias. Short because host-hop pays per-frame host materialization
    # AND per-shape recompiles — a full-length run would blow the
    # smoke budget for no extra signal.
    host_n = max(16, n_frames // 8)
    host_fps, _ = run(host_hop, host_n)
    _mark("composite host-hop measured")
    devcrop_fps, _ = run(device_crop)
    _mark("composite device-crop measured")
    fused_fps, fused_transfer = run(fused)
    _mark("composite fused measured")
    speedup = (
        round(fused_fps / host_fps, 3) if fused_fps and host_fps else None
    )
    print(json.dumps({
        "metric": "composite_fused_vs_host_hop_fps",
        "unit": "fps",
        "fused_fps": _round(fused_fps),
        "host_hop_fps": _round(host_fps),
        "device_crop_fps": _round(devcrop_fps),
        "speedup_vs_host_hop": speedup,
        # the zero-host-hop invariant: a device source + discarding sink
        # leaves NOTHING to fetch — any D2H here is a mid-chain
        # materialization (docs/on-device-ops.md)
        "fused_d2h_bytes": fused_transfer["d2h"],
        "n_frames": n_frames,
        "host_hop_n_frames": host_n,
        "platform": dev.platform,
        "device": str(dev.device_kind),
        "host": _platform.node(),
    }))


def _pipeline_edge(smoke: bool) -> None:
    """``--pipeline edge``: the fleet/fanout benchmark (ROADMAP item 5,
    docs/edge-serving.md "Running a fleet"), ONE JSON line. Cells:

    - ``one_endpoint_fps`` / ``three_endpoint_fps`` — aggregate
      request/reply throughput of N concurrent ``tensor_query_client``
      fleets against 1 vs 3 admission-bounded echo servers (loopback
      TCP; the fanout win is server-side parallelism + per-endpoint
      queues), and their ratio ``fanout_speedup``;
    - ``kill_failover_gap_ms`` — during the 3-endpoint run one server
      is HARD-killed mid-stream; the gap is the worst per-request
      latency the fleet observed around the kill (the failover cost);
    - ``kill_duplicate_replies`` / ``kill_failovers`` — at-most-once
      bookkeeping under the kill (duplicates must stay 0 delivered —
      the counter counts *dropped* late replies);
    - ``shm_rtt_fps`` / ``grpc_push_fps`` — optional same-host cells
      where the toolchain/grpcio are available (the zero-socket shm
      query pair and the gRPC bridge push path).

    ``--smoke`` shrinks counts; never run concurrently with a tier-1
    measurement."""
    import threading

    import numpy as np

    from nnstreamer_tpu.edge.query import TensorQueryClient
    from nnstreamer_tpu.pipeline.parse import parse_pipeline
    from nnstreamer_tpu.tensors.frame import Frame

    n_clients = 3 if smoke else 6
    # even --smoke keeps enough requests that the mid-run kill lands
    # INSIDE the traffic window (the gap cell nulls when it misses)
    n_requests = 120 if smoke else 200

    def start_server(tag: str):
        p = parse_pipeline(
            f"tensor_query_serversrc name={tag}-src port=0 id={tag} "
            "max-inflight=8 retry-after-ms=10 ! "
            "tensor_filter framework=passthrough input=64 "
            "inputtype=float32 ! "
            f"tensor_query_serversink id={tag}"
        )
        p.start()
        return p, p[f"{tag}-src"].bound_port

    def run_fleet(hosts: str, kill_fn=None):
        """N concurrent clients; returns (aggregate_fps, per-request
        (done_t, latency) list, summed fleet stats)."""
        lat = []
        stats = []
        mu = threading.Lock()

        def drive(i: int) -> None:
            c = TensorQueryClient(
                f"bench-edge-c{i}",
                **{"hosts": hosts, "timeout": 10, "retry-max": 8,
                   "retry-backoff-ms": 10},
            )
            c.start()
            try:
                for j in range(n_requests):
                    t0 = time.perf_counter()
                    c.process(Frame((np.full(64, float(j), np.float32),)))
                    done = time.perf_counter()
                    with mu:
                        lat.append((done, done - t0))
            finally:
                with mu:
                    stats.append(c.fleet_stats())
                c.stop()

        threads = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if kill_fn is not None:
            kill_fn()
        for t in threads:
            t.join(timeout=300)
        wall = time.perf_counter() - t0
        fps = len(lat) / wall if wall > 0 else None
        agg = {
            "failovers": sum(s.get("failovers", 0) for s in stats),
            "duplicate_replies": sum(
                s.get("duplicate_replies", 0) for s in stats
            ),
        }
        return fps, lat, agg

    # cell 1: one endpoint
    p1, port1 = start_server("bedge1")
    one_fps, _lat1, _ = run_fleet(f"127.0.0.1:{port1}")
    p1.stop()
    _mark("edge 1-endpoint measured")

    # cell 2: three endpoints, then the mid-run kill
    servers = [start_server(f"bedge3{i}") for i in range(3)]
    hosts3 = ",".join(f"127.0.0.1:{port}" for _p, port in servers)
    three_fps, _lat3, _ = run_fleet(hosts3)
    _mark("edge 3-endpoint measured")

    kill_at_s = max(0.05, 0.3 * len(_lat3) / (three_fps or 1000.0))
    killed = {}

    def kill_one():
        def _later():
            time.sleep(kill_at_s)
            servers[0][0].stop()
            killed["t"] = time.perf_counter()
        threading.Thread(target=_later, daemon=True).start()

    kill_fps, kill_lat, kill_agg = run_fleet(hosts3, kill_fn=kill_one)
    for p, _port in servers[1:]:
        p.stop()
    _mark("edge kill cell measured")

    # optional same-host transport cells
    shm_fps = grpc_fps = None
    try:
        from nnstreamer_tpu.edge.query_transports import (
            ShmClientTransport,
            ShmServerTransport,
        )

        srv = ShmServerTransport()
        port = srv.listen("", 0)
        cli = ShmClientTransport()
        cli.connect("", port)
        blob = b"x" * 4096
        stop = threading.Event()

        def echo():
            while not stop.is_set():
                got = srv.recv(timeout=0.1)
                if got is not None:
                    srv.send(got[0], got[1])

        t = threading.Thread(target=echo, daemon=True)
        t.start()
        n = 200 if smoke else 2000
        t0 = time.perf_counter()
        for _ in range(n):
            cli.send(0, blob)
            cli.recv(timeout=5)
        shm_fps = n / (time.perf_counter() - t0)
        stop.set()
        t.join(timeout=2)
        cli.close()
        srv.close()
    except Exception:  # noqa: BLE001 — toolchain-gated optional cell
        pass
    try:
        import grpc  # noqa: F401

        from nnstreamer_tpu.edge.grpc_bridge import (
            GrpcTensorSink,
            GrpcTensorSrc,
        )

        gsrc = GrpcTensorSrc("bench-gsrc", server="true", port=0)
        gsrc.start()
        gsink = GrpcTensorSink(
            "bench-gsink", server="false", port=gsrc.bound_port
        )
        gsink.start()
        frame = Frame((np.zeros(64, np.float32),))
        n = 200 if smoke else 2000
        got = 0
        t0 = time.perf_counter()
        for _ in range(n):
            gsink.render(frame)
        while got < n and time.perf_counter() - t0 < 60:
            if gsrc.generate() is not None:
                got += 1
        grpc_fps = got / (time.perf_counter() - t0)
        gsink.stop()
        gsrc.stop()
    except Exception:  # noqa: BLE001 — grpcio-gated optional cell
        pass

    # failover gap: the worst request latency among requests completing
    # AFTER the kill landed (pre-kill cold-start spikes must not read
    # as failover cost); null when the kill missed the traffic window.
    # Duplicates counted are DROPPED late replies — delivered
    # duplicates are impossible by the frame_id dedup, which the fleet
    # tests pin
    gap_ms = None
    kill_t = killed.get("t")
    if kill_t is not None:
        post = [l for (done, l) in kill_lat if done >= kill_t]
        if post:
            gap_ms = max(post) * 1000.0
    rec = {
        "metric": "edge_fleet_fanout",
        "unit": "fps",
        "one_endpoint_fps": _round(one_fps),
        "three_endpoint_fps": _round(three_fps),
        "fanout_speedup": (
            round(three_fps / one_fps, 3) if one_fps and three_fps else None
        ),
        "kill_fps": _round(kill_fps),
        "kill_failover_gap_ms": _round(gap_ms),
        "kill_failovers": kill_agg["failovers"],
        "kill_duplicate_replies": kill_agg["duplicate_replies"],
        "shm_rtt_fps": _round(shm_fps) if shm_fps else None,
        "grpc_push_fps": _round(grpc_fps) if grpc_fps else None,
        "n_clients": n_clients,
        "n_requests": n_requests,
    }
    print(json.dumps(rec))


def _pipeline_llm(smoke: bool) -> None:
    """``--pipeline llm``: paged-vs-slot KV capacity at ONE fixed KV
    HBM budget (models/serving.py kv_layout, docs/llm-serving.md), ONE
    JSON line next to the lm-cb cells of the full record. Two numbers:

    - live-request capacity: the slot layout holds exactly
      ``budget_tokens / max_len`` requests by construction; the paged
      layout admits until its watermark defers — the acceptance bar is
      ≥ 2× at the same budget, with a shared system prompt exercising
      prefix sharing (``nns_kv_prefix_hits_total`` must be > 0);
    - decode tok/s at EQUAL occupancy (the capacity win must not cost
      the decode path).

    ``--smoke`` pins CPU and shrinks the model; never run concurrently
    with a tier-1 measurement."""
    import jax
    import jax.numpy as jnp

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    rng = np.random.default_rng(0)
    if on_tpu:
        model_kw = dict(vocab=32000, d_model=512, n_heads=8, n_layers=4)
        dtype = jnp.bfloat16
    else:
        model_kw = dict(vocab=512, d_model=64, n_heads=4, n_layers=2)
        dtype = jnp.float32
    params = tfm.init_params(jax.random.PRNGKey(7), **model_kw)
    n_heads = model_kw["n_heads"]
    max_len, prompt_len, block_size = 192, 32, 16
    slot_slots = 6
    budget_tokens = slot_slots * max_len  # the fixed KV HBM budget
    kv_blocks = budget_tokens // block_size
    sys_prompt = np.tile(
        rng.integers(1, model_kw["vocab"], (32,)), 2
    ).astype(np.int32)[:64]  # 4 shared blocks
    decode_budget = 24

    def _prompt(i):
        return np.concatenate(
            [sys_prompt,
             rng.integers(1, model_kw["vocab"], (16,)).astype(np.int32)]
        )

    def _mk(layout, n_slots):
        kw = dict(compute_dtype=dtype)
        if layout == "paged":
            kw.update(kv_layout="paged", block_size=block_size,
                      kv_blocks=kv_blocks)
        return ContinuousBatcher(
            params, n_heads, n_slots=n_slots, max_len=max_len,
            prompt_len=prompt_len, **kw,
        )

    def _capacity(cb, n_try):
        """Admit until the batcher defers (slot: submit() returns None;
        paged: a submitted request stays un-activated because the
        watermark would be breached) — peak concurrently-live
        requests at this KV budget."""
        rids = []
        live = 0
        for i in range(n_try):
            rid = cb.submit(_prompt(i), decode_budget)
            if rid is None:
                break
            rids.append(rid)
            for _ in range(8):  # let prefill/activation settle
                cb.step_pump(1)
                st = cb.stats()
                if st.get("kv_prefill_queue", 0) == 0:
                    break
            st = cb.stats()
            if st.get("kv_prefill_queue", 0) > 0:  # watermark deferred
                break
            if st.get("kv_preemptions", 0) > 0:
                break
            live = sum(
                1 for r in rids
                if cb.result(r) is None
            )
        while any(cb.result(r) is None for r in rids):
            cb.step_pump(8)
        return live, cb.stats()

    slot_cap, _ = _capacity(_mk("slot", slot_slots), 64)
    _mark("slot capacity measured")
    paged_cap, paged_st = _capacity(_mk("paged", 64), 64)
    _mark("paged capacity measured")

    tok_budget = 64  # decode window of the tok/s cells (not capacity's)
    tok_prompts = [_prompt(100 + i) for i in range(slot_slots)]
    slot_tok_s = _llm_equal_occupancy_tok_s(
        _mk("slot", slot_slots), tok_prompts, tok_budget
    )
    _mark("slot tok/s measured")
    paged_tok_s = _llm_equal_occupancy_tok_s(
        _mk("paged", slot_slots), tok_prompts, tok_budget
    )
    _mark("paged tok/s measured")
    plane_cell = _llm_through_plane_cell(model_kw, rng) or {}
    _mark("through-plane measured")
    disagg_cell = _llm_disagg_cell(model_kw, rng) or {}
    _mark("disagg measured")
    rec = {
        "metric": "llm_paged_vs_slot_capacity_at_fixed_kv_hbm",
        "kv_budget_tokens": budget_tokens,
        "block_size": block_size,
        "max_len": max_len,
        "decode_budget": decode_budget,  # the capacity cells' budget
        "tok_s_budget": tok_budget,      # the equal-occupancy tok/s cells'
        "slot_capacity": slot_cap,
        "paged_capacity": paged_cap,
        "capacity_ratio": (
            round(paged_cap / slot_cap, 2) if slot_cap else None
        ),
        "slot_tok_s": _round(slot_tok_s, 1),
        "paged_tok_s": _round(paged_tok_s, 1),
        "tok_s_ratio": (
            round(paged_tok_s / slot_tok_s, 3) if slot_tok_s else None
        ),
        # the gate key (GATE_KEYS): paged/slot decode tok/s at equal
        # occupancy — ≥ 0.95 is the block-native acceptance bar, a
        # regression fails `bench.py --gate` against a fresh reference
        "paged_tok_frac": (
            round(paged_tok_s / slot_tok_s, 3) if slot_tok_s else None
        ),
        "kv_attn": paged_st.get("kv_attn"),
        "kv_gather_dispatches": paged_st.get("kv_gather_dispatches", 0),
        "nns_kv_prefix_hits_total": paged_st.get("kv_prefix_hits", 0),
        "kv_prefix_hit_tokens": paged_st.get("kv_prefix_hit_tokens", 0),
        "kv_preemptions": paged_st.get("kv_preemptions", 0),
        "platform": dev.platform,
        "device": str(dev.device_kind),
        "host": _platform.node(),
    }
    rec.update(plane_cell)
    rec.update(disagg_cell)
    print(json.dumps(rec))


def _llm_through_plane_cell(model_kw: dict, rng) -> dict | None:
    """LLM pumps batched THROUGH a serving plane (serving_plane/llm.py,
    docs/llm-serving.md): two serversink/serversrc pipeline pairs share
    ONE plane-managed paged ContinuousBatcher (``plane=`` on the
    serversink) — cross-stream admission rides the deficit-round-robin
    scheduler, SLO ledgers stay per stream, and the block-native decode
    path must stay gather-free (``llm_plane_gather_dispatches`` pinned
    0 in the record)."""
    import threading

    import numpy as np

    from nnstreamer_tpu.elements.llm_serve import (
        LlmServerSink,
        LlmServerSrc,
    )
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    opts = ",".join(
        f"{k}:{v}" for k, v in model_kw.items()
    ) + ",seed:7"
    n_streams, n_reqs, budget = 2, 4, 24
    pipes, ends = [], []
    for k in range(n_streams):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(**{
            "id": f"bench_pl{k}", "model": "zoo:transformer_lm",
            "custom": opts, "n-slots": 8, "max-len": 96,
            "prompt-len": 32, "max-new-tokens": budget, "pump": 4,
            "plane": "llm_bench", "block-size": 16, "kv-blocks": 48,
        })
        osrc = LlmServerSrc(**{"id": f"bench_pl{k}"})
        osink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(osrc, osink)
        p.start()
        pipes.append(p)
        ends.append((src, osink, osrc))
    try:
        t0 = time.perf_counter()
        for k, (src, _, _) in enumerate(ends):
            for i in range(n_reqs):
                prompt = rng.integers(
                    1, model_kw["vocab"], (16 + 4 * i,)
                ).astype(np.int32)
                src.push(Frame((prompt,), meta={"req": f"s{k}r{i}"}))
            src.end_of_stream()
        stream_toks = [0] * n_streams
        errors = []
        per_stream_reqs = []

        def drain(k):
            try:
                _, osink, _ = ends[k]
                for _ in range(n_reqs):
                    f = osink.pop(timeout=300)
                    if f is None:
                        raise RuntimeError(
                            "llm plane cell drained early"
                        )
                    stream_toks[k] += int(np.asarray(f.tensors[0]).size)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((k, exc))

        threads = [
            threading.Thread(target=drain, args=(k,))
            for k in range(n_streams)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            # a partial drain must fail the cell, not publish a tok/s
            # computed from whatever happened to arrive
            raise RuntimeError(f"llm plane cell failures: {errors!r}")
        toks = sum(stream_toks)
        dt = time.perf_counter() - t0
        st = None
        for _, _, osrc in ends:
            got = osrc.serving_stats()
            if got:
                per_stream_reqs.append(len(got.get("requests", {})))
                if st is None:
                    st = got
    finally:
        for p in pipes:
            p.stop()
    if st is None:
        return None
    return {
        "llm_plane_streams": n_streams,
        "llm_plane_requests_per_stream": n_reqs,
        "llm_plane_tok_s": _round(toks / dt if dt > 0 else 0.0, 1),
        "llm_plane_gather_dispatches": st.get("kv_gather_dispatches", 0),
        "llm_plane_kv_attn": st.get("kv_attn"),
        # per-stream SLO ledgers: each src reports ONLY its own rows
        "llm_plane_stream_request_rows": per_stream_reqs,
    }


def _llm_disagg_cell(model_kw: dict, rng) -> dict | None:
    """Disaggregated prefill/decode vs colocated serving (serving_plane/
    disagg.py, docs/llm-serving.md "Disaggregated serving"): the same
    request set runs once on a single colocated paged server and once
    split across a role=prefill server handing KV spans to a
    role=decode peer over the real CTRL channel. Two columns of
    aggregate decode tok/s plus TTFT p50/p99 from the submitting
    server's SLO ledger (the first token always materializes on the
    prefill engine before extraction, so the latency rows are
    apples-to-apples), and the decode side's ``kv_prefill_chunks``
    counter pinned at 0 — the handoff must re-prefill nothing."""
    import threading

    import numpy as np

    from nnstreamer_tpu.edge.query import TensorQueryServerSrc
    from nnstreamer_tpu.elements.llm_serve import _LlmServer
    from nnstreamer_tpu.tensors.frame import Frame

    opts = {k: str(v) for k, v in model_kw.items()}
    opts["seed"] = "7"
    n_reqs, budget = 6, 24
    prompts = [
        rng.integers(1, model_kw["vocab"], (16 + 2 * i,)).astype(np.int32)
        for i in range(n_reqs)
    ]

    def _mk_srv(srv_id, **kw):
        return _LlmServer(
            model="zoo:transformer_lm", options=dict(opts), n_slots=8,
            max_len=96, prompt_len=32, default_new=budget,
            kv_layout="paged", block_size=16, kv_blocks=64,
            srv_id=srv_id, **kw,
        )

    def _run(srv):
        """Submit the request set, pump to completion; returns
        (tok_s, sorted ttft_ms rows from the SLO ledger)."""
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            srv.submit(Frame((p,), meta={"req": f"dg{i}"}))
        deadline = t0 + 300.0
        n_toks = 0
        done = 0
        while done < n_reqs:
            if time.perf_counter() > deadline:
                raise RuntimeError("llm disagg cell drained early")
            srv.pump()
            while srv._out:
                toks, _meta = srv.pop()
                n_toks += len(toks)
                done += 1
        dt = time.perf_counter() - t0
        ttfts = sorted(
            row["ttft_ms"] for row in srv.cb.requests().values()
            if row.get("ttft_ms") is not None
        )
        return (n_toks / dt if dt > 0 else 0.0), ttfts

    def _pct(rows, q):
        if not rows:
            return None
        return _round(rows[min(len(rows) - 1, int(q * (len(rows) - 1)))], 1)

    colo = _mk_srv("9300")
    try:
        colo_tok_s, colo_ttfts = _run(colo)
    finally:
        colo.release_plane()

    decode = _mk_srv("9301", role="decode")
    src = TensorQueryServerSrc("bench-disagg-d", port=0, id="bench-dg")
    src.start()
    stop = threading.Event()

    def _ctrl():
        while not stop.is_set():
            src.generate()

    def _pump():
        while not stop.is_set():
            try:
                decode.pump()
            except Exception:  # noqa: BLE001 — teardown race
                pass
            time.sleep(0.001)

    threads = [threading.Thread(target=_ctrl, daemon=True),
               threading.Thread(target=_pump, daemon=True)]
    for t in threads:
        t.start()
    prefill = _mk_srv(
        "9302", role="prefill",
        decode_peers=f"127.0.0.1:{src.bound_port}/9301",
    )
    try:
        dis_tok_s, dis_ttfts = _run(prefill)
        decode_chunks = decode.cb.stats().get("kv_prefill_chunks", -1)
        counts = prefill.stats().get("disagg", {}).get("counts", {})
    finally:
        prefill.release_plane()
        stop.set()
        for t in threads:
            t.join(timeout=2)
        src.stop()
        decode.release_plane()
    return {
        "llm_disagg_requests": n_reqs,
        "llm_colocated_tok_s": _round(colo_tok_s, 1),
        "llm_disagg_tok_s": _round(dis_tok_s, 1),
        "llm_colocated_ttft_p50_ms": _pct(colo_ttfts, 0.5),
        "llm_colocated_ttft_p99_ms": _pct(colo_ttfts, 0.99),
        "llm_disagg_ttft_p50_ms": _pct(dis_ttfts, 0.5),
        "llm_disagg_ttft_p99_ms": _pct(dis_ttfts, 0.99),
        # the zero-re-prefill pin: every span adopted whole, no chunk
        # program ever ran on the decode peer
        "llm_disagg_decode_prefill_chunks": decode_chunks,
        "llm_disagg_handoffs": counts.get("handoff", 0),
        "llm_disagg_relayed": counts.get("relayed", 0),
    }


def main() -> None:
    if "--probe" in sys.argv:
        return _probe()
    if "--run" in sys.argv:
        return _run()
    if "--watch" in sys.argv:
        return _watch()
    if "--gate" in sys.argv:
        return _gate()
    if "--capture-measured" in sys.argv:
        return _capture_measured()
    if "--capture-tpu" in sys.argv:
        return _capture_tpu()
    if "--pipeline" in sys.argv:
        mode = sys.argv[sys.argv.index("--pipeline") + 1 :][:1]
        if mode == ["batched"]:
            return _pipeline_batched("--smoke" in sys.argv)
        if mode == ["plane"]:
            return _pipeline_plane("--smoke" in sys.argv)
        if mode == ["llm"]:
            return _pipeline_llm("--smoke" in sys.argv)
        if mode == ["composite"]:
            return _pipeline_composite("--smoke" in sys.argv)
        if mode == ["edge"]:
            return _pipeline_edge("--smoke" in sys.argv)
        print(f"unknown --pipeline mode {mode}", file=sys.stderr)
        return 2

    import subprocess

    here = os.path.abspath(__file__)
    # (delay_before_attempt, extra_env, per_attempt_timeout). The first
    # attempt gets the full window; retries get short windows so a WEDGED
    # attach (jax.devices() blocking for minutes, observed after an
    # ungraceful TPU-process death) still leaves time for the final
    # CPU-pinned attempt — a diagnostic number always beats rc:1/124.
    attempts = [
        (0, {}, 1500),
        (5, {}, 420),
        (15, {}, 420),
        (30, {}, 420),
        (5, {"BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu"}, 600),
    ]
    if _tunnel_alive() is False:
        print(
            "[bench] accelerator relay unreachable; skipping straight to "
            "the CPU diagnostic attempt",
            file=sys.stderr,
        )
        attempts = [(0, *attempts[-1][1:])]  # no backoff delay needed
    elif not _tpu_attachable(here):
        # relay up but the TPU claim is wedged (attach blocks for tens of
        # minutes): keep ONE full TPU window in case the wedge clears
        # mid-window, then the CPU diagnostic — but skip the short
        # retries, which a wedge would eat whole
        print(
            "[bench] TPU attach probes kept failing (wedged claim); "
            "keeping one full TPU window then the CPU fallback",
            file=sys.stderr,
        )
        attempts = [attempts[0], attempts[-1]]
    last_tail = ""
    for delay, extra, attempt_timeout in attempts:
        if delay:
            time.sleep(delay)
        env = dict(os.environ, **extra)
        # the child must give up on optional sections well before ITS
        # hard timeout, or a slow attempt loses the already-measured
        # primary metrics to a SIGKILL
        env.setdefault(
            "BENCH_SOFT_BUDGET_S", str(max(attempt_timeout - 150, 120))
        )
        try:
            p = subprocess.run(
                [sys.executable, here, "--run"],
                capture_output=True,
                text=True,
                timeout=attempt_timeout,
                env=env,
            )
        except subprocess.TimeoutExpired as exc:
            last_tail = f"timeout after {exc.timeout}s"
            continue
        if p.returncode == 0:
            for line in reversed(p.stdout.strip().splitlines()):
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    print(line)
                    _record_measured(line)
                    return
        last_tail = (p.stdout + "\n" + p.stderr)[-1200:]
    print(
        json.dumps(
            {
                "metric": "mobilenet_v2_224_bs1_fps_per_chip",
                "value": None,
                "unit": "fps",
                "vs_baseline": None,
                "error": "all bench attempts failed (incl. cpu fallback)",
                "tail": last_tail,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
