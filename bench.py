#!/usr/bin/env python
"""Benchmark: MobileNet-v2 224x224 single-chip streaming FPS.

The BASELINE.md north-star config: the reference's gst-launch MobileNet-v2
image-labeling pipeline, rebuilt TPU-native — uint8 frames in, logits out,
normalization fused into the jitted model, frames streamed with async
dispatch-ahead. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "fps", "vs_baseline": N, ...}
vs_baseline is against the 1000 FPS/chip target (BASELINE.json).

Measurement notes: jax dispatch is async; a streaming pipeline only
synchronizes when a sink consumes results on host. We sync on a bounded
in-flight window — the executor's sink path with ``sync-window=N``
(elements/base.py Sink, executor.py SinkNode) — which is the steady-state
pattern, not a per-frame round-trip (the tunnelled device adds ~70ms per
*sync*, not per dispatch, so per-frame blocking would measure the tunnel,
not the TPU).
"""

from __future__ import annotations

import json
import statistics
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.models import zoo

    batch = 1
    iters = 1024
    warmup = 20
    sync_every = 256  # bounded in-flight window (256 frames ≈ 40 MB on-device)

    m = zoo.get("mobilenet_v2", batch=str(batch), compute_dtype="bfloat16")
    fn = jax.jit(m.fn)
    rng = np.random.default_rng(0)
    frames = [
        jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3), np.uint8))
        for _ in range(8)
    ]

    # warmup / compile
    out = None
    for i in range(warmup):
        out = fn(frames[i % len(frames)])
    jax.block_until_ready(out)

    # throughput: stream with bounded dispatch-ahead window. The device
    # runs dispatches in order, so syncing the window's LAST result fences
    # the whole window without touching every handle.
    t0 = time.perf_counter()
    out = None
    for i in range(iters):
        out = fn(frames[i % len(frames)])
        if (i + 1) % sync_every == 0:
            out.block_until_ready()
    out.block_until_ready()
    dt = time.perf_counter() - t0
    fps = iters * batch / dt

    # p50 sync round-trip latency (includes device-tunnel RTT when remote)
    lat = []
    for i in range(50):
        t = time.perf_counter()
        fn(frames[i % len(frames)]).block_until_ready()
        lat.append((time.perf_counter() - t) * 1000)
    p50 = statistics.median(lat)

    # micro-batched variant: the reference's converter frames-per-tensor
    # batching (gsttensor_converter.c frames_per_tensor) maps to the
    # aggregator batching 8 frames per invoke — same pipeline semantics,
    # amortizing the per-dispatch cost the bs1 number is bound by.
    mb = 8
    m8 = zoo.get("mobilenet_v2", batch=str(mb), compute_dtype="bfloat16")
    fn8 = jax.jit(m8.fn)
    frames8 = [
        jnp.asarray(rng.integers(0, 255, (mb, 224, 224, 3), np.uint8))
        for _ in range(4)
    ]
    out = fn8(frames8[0])
    jax.block_until_ready(out)
    iters8 = 256
    t0 = time.perf_counter()
    for i in range(iters8):
        out = fn8(frames8[i % 4])
        if (i + 1) % 64 == 0:
            out.block_until_ready()
    out.block_until_ready()
    mb_fps = iters8 * mb / (time.perf_counter() - t0)

    dev = jax.devices()[0]
    print(
        json.dumps(
            {
                "metric": "mobilenet_v2_224_bs1_fps_per_chip",
                "value": round(fps, 1),
                "unit": "fps",
                "vs_baseline": round(fps / 1000.0, 3),
                "p50_sync_latency_ms": round(p50, 3),
                "amortized_frame_ms": round(dt / iters * 1000, 3),
                "microbatch8_fps": round(mb_fps, 1),
                "platform": dev.platform,
                "device": str(dev.device_kind),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
